"""Recovery strategies for managed (preemptible) jobs.

Parity: sky/jobs/recovery_strategy.py — StrategyExecutor registry (:98),
launch with retry/backoff + wait-for-RUNNING (:127,:194), FAILOVER (:395)
and EAGER_NEXT_REGION (:483), re-cast at TPU-slice granularity:

- The dominant failure is *zone stockout of a whole slice*, so the
  default strategy is EAGER_NEXT_ZONE: after a preemption, immediately
  deprioritize the zone that preempted us and try the optimizer's next
  ranked placement.
- TPU slices cannot be restarted after preemption — the remnant must be
  *deleted* before relaunching (parity:
  `need_cleanup_after_preemption_or_failure`, sky/resources.py:622).
"""
import time
from typing import Callable, Dict, Optional, Type

from skypilot_tpu import exceptions, execution, logsys, state
from skypilot_tpu.jobs import constants
from skypilot_tpu.task import Task

logger = logsys.init_logger(__name__)

RECOVERY_STRATEGIES: Dict[str, Type['StrategyExecutor']] = {}
DEFAULT_RECOVERY_STRATEGY = 'EAGER_NEXT_ZONE'


class JobCancelledDuringRecovery(exceptions.SkyTpuError):
    """Raised from launch/recover when the cancel signal arrives mid-retry
    (a stockout-stuck recovery is exactly when users cancel)."""


class StrategyExecutor:
    """Handles one task's cluster lifecycle: launch, recover, cleanup."""

    NAME = 'base'

    def __init__(self, cluster_name: str, task: Task,
                 should_cancel: Optional[Callable[[], bool]] = None):
        self.cluster_name = cluster_name
        self.task = task
        self._should_cancel = should_cancel or (lambda: False)

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if cls.NAME in RECOVERY_STRATEGIES:
            raise ValueError(f'Duplicate strategy name {cls.NAME}')
        RECOVERY_STRATEGIES[cls.NAME] = cls

    @classmethod
    def make(cls, cluster_name: str, task: Task,
             should_cancel: Optional[Callable[[], bool]] = None
             ) -> 'StrategyExecutor':
        name = (task.get_preferred_resources().job_recovery or
                DEFAULT_RECOVERY_STRATEGY).upper()
        if name not in RECOVERY_STRATEGIES:
            raise exceptions.InvalidResourcesError(
                f'Unknown job recovery strategy {name!r}; available: '
                f'{sorted(RECOVERY_STRATEGIES)}')
        return RECOVERY_STRATEGIES[name](cluster_name, task, should_cancel)

    # ------------------------------------------------------------- lifecycle

    def launch(self, max_retries: Optional[int] =
               constants.MAX_INITIAL_LAUNCH_RETRIES) -> float:
        """Provision the cluster and wait until the job is RUNNING.
        Returns the timestamp the job started.  Raises
        ResourcesUnavailableError after ``max_retries`` failed rounds
        (None = retry forever)."""
        return self._launch(max_retries)

    def recover(self) -> float:
        """Relaunch after a preemption/failure; returns job start time.
        Subclasses choose the placement order.  Retries forever."""
        raise NotImplementedError

    def cleanup_cluster(self) -> None:
        """Delete the (possibly half-dead) cluster.  TPU remnants MUST be
        deleted, never stopped."""
        record = state.get_cluster_from_name(self.cluster_name)
        if record is None:
            return
        from skypilot_tpu.backends import SliceBackend
        try:
            SliceBackend().teardown(record['handle'], terminate=True,
                                    purge=True)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('Cleanup of %r failed (ignored): %s',
                           self.cluster_name, e)
            # Last resort: drop the record so a relaunch is not blocked.
            try:
                state.remove_cluster(self.cluster_name, terminate=True)
            except Exception:  # pylint: disable=broad-except
                pass

    # -------------------------------------------------------------- internal

    def _current_zone(self) -> Optional[str]:
        record = state.get_cluster_from_name(self.cluster_name)
        if record is None:
            return None
        return record['handle'].launched_resources.zone

    def _deprioritize_zone(self, zone: Optional[str]) -> None:
        """Move candidates in ``zone`` to the end of the ranked list."""
        cands = getattr(self.task, 'candidates', None)
        if not cands or zone is None:
            return
        good = [c for c in cands if c.zone != zone]
        bad = [c for c in cands if c.zone == zone]
        if good:
            self.task.candidates = good + bad
            self.task.best_resources = good[0].resources

    def _prioritize_zone(self, zone: Optional[str]) -> None:
        """Move candidates in ``zone`` to the front (same-placement retry)."""
        cands = getattr(self.task, 'candidates', None)
        if not cands or zone is None:
            return
        same = [c for c in cands if c.zone == zone]
        rest = [c for c in cands if c.zone != zone]
        if same:
            self.task.candidates = same + rest
            self.task.best_resources = same[0].resources

    def _launch(self, max_retries: Optional[int]) -> float:
        attempt = 0
        backoff = constants.RETRY_INIT_GAP_SECONDS
        while True:
            if self._should_cancel():
                raise JobCancelledDuringRecovery(self.cluster_name)
            attempt += 1
            try:
                job_id = execution.launch(self.task,
                                          cluster_name=self.cluster_name,
                                          detach_run=True,
                                          stream_logs=False)
                start = self._wait_until_job_starts(job_id)
                if start is not None:
                    return start
                raise exceptions.JobError(
                    f'Job on {self.cluster_name!r} did not reach RUNNING.')
            except Exception as e:  # pylint: disable=broad-except
                logger.warning('Launch attempt %d for %r failed: %s',
                               attempt, self.cluster_name, e)
                self.cleanup_cluster()
                if max_retries is not None and attempt >= max_retries:
                    raise exceptions.ResourcesUnavailableError(
                        f'Failed to launch the job cluster after '
                        f'{attempt} attempt(s): {e}') from e
                slept = 0.0
                while slept < backoff:  # interruptible backoff
                    if self._should_cancel():
                        raise JobCancelledDuringRecovery(self.cluster_name)
                    time.sleep(min(2.0, backoff - slept))
                    slept += 2.0
                backoff = min(backoff * 2, 300)

    def _wait_until_job_starts(self, job_id: Optional[int],
                               timeout: float = 3600) -> Optional[float]:
        """Poll the job cluster's podlet until the job is RUNNING (or
        terminal).  Parity: _wait_until_job_starts_on_cluster
        (sky/jobs/recovery_strategy.py:194)."""
        from skypilot_tpu.backends import SliceBackend
        from skypilot_tpu.podlet import job_lib
        if job_id is None:
            return None
        backend = SliceBackend()
        record = state.get_cluster_from_name(self.cluster_name)
        if record is None:
            return None
        handle = record['handle']
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._should_cancel():
                raise JobCancelledDuringRecovery(self.cluster_name)
            try:
                status = backend.get_job_status(handle, job_id)['status']
            except Exception:  # pylint: disable=broad-except
                return None  # cluster gone mid-wait
            if status == job_lib.JobStatus.RUNNING.value:
                return time.time()
            if status is not None and job_lib.JobStatus(
                    status).is_terminal():
                # Finished before we saw RUNNING (very short jobs): fine.
                return time.time()
            time.sleep(constants.JOB_STARTED_CHECK_GAP_SECONDS)
        return None


class BatchRowRecovery:
    """Row-level recovery policy for the serve-plane bulk-inference
    coordinator (serve/batch.py).  Rows are not clusters: a failed row
    re-enters the job's pending queue (the fleet's PR 5 failover plus
    the LB retry budget are the transport-level recovery), so the only
    policy here is how patiently the coordinator retries before the
    completion window declares the row lost.

    Kept in this module so the jobs plane owns ALL recovery policy —
    the serve side asks for a policy, it never invents one."""

    def __init__(self, max_attempts: int = 8,
                 init_backoff_s: float = 0.2,
                 max_backoff_s: float = 5.0):
        if max_attempts < 1:
            raise ValueError('max_attempts must be >= 1')
        self.max_attempts = max_attempts
        self.init_backoff_s = init_backoff_s
        self.max_backoff_s = max_backoff_s

    def should_retry(self, attempt: int,
                     window_remaining_s: float) -> bool:
        """attempt is 1-based: the count of failures so far."""
        return attempt < self.max_attempts and window_remaining_s > 0.0

    def backoff_s(self, attempt: int) -> float:
        """Exponential, capped — deterministic (no jitter): the batch
        plane's byte-identity contract wants replayable schedules."""
        return min(self.max_backoff_s,
                   self.init_backoff_s * (2 ** max(0, attempt - 1)))


class EagerNextZoneExecutor(StrategyExecutor):
    """After preemption/stockout, immediately move to the optimizer's next
    ranked zone (the preempting zone goes to the back of the line).
    Parity: EAGER_NEXT_REGION (sky/jobs/recovery_strategy.py:483), at zone
    granularity because a TPU slice lives entirely in one zone."""

    NAME = 'EAGER_NEXT_ZONE'

    def recover(self) -> float:
        zone = self._current_zone()
        self.cleanup_cluster()
        self._deprioritize_zone(zone)
        return self._launch(max_retries=None)


class FailoverExecutor(StrategyExecutor):
    """Retry the same zone first (data locality / reservation affinity),
    then fail over.  Parity: FAILOVER
    (sky/jobs/recovery_strategy.py:395)."""

    NAME = 'FAILOVER'

    def recover(self) -> float:
        zone = self._current_zone()
        self.cleanup_cluster()
        self._prioritize_zone(zone)
        return self._launch(max_retries=None)
