"""Managed-jobs dashboard: a small HTTP view of the jobs queue.

Parity: sky/jobs/dashboard/dashboard.py (flask on the controller,
port-forwarded by `sky jobs dashboard`) — rebuilt on stdlib http.server
(flask is not a dependency of this framework) and run client-side: it
queries the controller over the same codegen RPC the CLI uses, so there
is nothing to port-forward.

Endpoints: `/` (HTML table, auto-refresh), `/api/jobs` (JSON).
"""
import html
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import logsys

logger = logsys.init_logger(__name__)

_REFRESH_SECONDS = 30

_PAGE = """<!doctype html>
<html><head><title>skytpu jobs</title>
<meta http-equiv="refresh" content="{refresh}">
<style>
 body {{ font-family: monospace; margin: 2em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
 th {{ background: #eee; }}
 .RUNNING {{ color: #0a0; }} .SUCCEEDED {{ color: #06c; }}
 .FAILED, .FAILED_SETUP, .FAILED_CONTROLLER {{ color: #c00; }}
 .RECOVERING {{ color: #c80; }} .CANCELLED {{ color: #888; }}
</style></head>
<body><h2>Managed jobs</h2>
<p>{count} job task(s); refreshed {now} (auto-refresh {refresh}s)</p>
<table><tr>{headers}</tr>{rows}</table>
</body></html>
"""

_COLUMNS = [
    ('job_id', 'ID'), ('job_name', 'NAME'), ('task_id', 'TASK'),
    ('status', 'STATUS'), ('cluster_name', 'CLUSTER'),
    ('submitted_at', 'SUBMITTED'), ('recovery_count', 'RECOVERIES'),
]


def _fetch_jobs() -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import core as jobs_core
    # Bypass the @usage.entrypoint wrapper: browser auto-refresh polling is
    # machine-generated and would flood the usage spool (one record / 30s).
    queue = getattr(jobs_core.queue, '__wrapped__', jobs_core.queue)
    return queue()


def _render(jobs: List[Dict[str, Any]]) -> str:
    headers = ''.join(f'<th>{h}</th>' for _, h in _COLUMNS)
    rows = []
    for j in jobs:
        cells = []
        for key, _ in _COLUMNS:
            val = j.get(key, '')
            if key == 'submitted_at' and val:
                val = time.strftime('%Y-%m-%d %H:%M:%S',
                                    time.localtime(float(val)))
            cells.append(f'<td class="{html.escape(str(j.get("status", "")))}">'
                         f'{html.escape(str(val))}</td>')
        rows.append('<tr>' + ''.join(cells) + '</tr>')
    return _PAGE.format(refresh=_REFRESH_SECONDS, count=len(jobs),
                        now=time.strftime('%H:%M:%S'), headers=headers,
                        rows=''.join(rows))


class _Handler(BaseHTTPRequestHandler):

    def log_message(self, fmt, *args):  # quiet access log -> logger.debug
        logger.debug('dashboard: ' + fmt, *args)

    def _send(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (stdlib API name)
        try:
            if self.path.startswith('/api/jobs'):
                body = json.dumps(_fetch_jobs(), default=str).encode()
                self._send(200, 'application/json', body)
            elif self.path == '/' or self.path.startswith('/?'):
                self._send(200, 'text/html; charset=utf-8',
                           _render(_fetch_jobs()).encode())
            else:
                self._send(404, 'text/plain', b'not found')
        except Exception as e:  # pylint: disable=broad-except
            self._send(500, 'text/plain',
                       f'error fetching jobs: {e}'.encode())


def start_dashboard(host: str = '127.0.0.1', port: int = 8765,
                    background: bool = False
                    ) -> Tuple[ThreadingHTTPServer, Optional[threading.Thread]]:
    """Serve the dashboard; blocks unless background=True."""
    server = ThreadingHTTPServer((host, port), _Handler)
    if background:
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server, thread
    logger.info('Dashboard at http://%s:%d/', host, port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return server, None
