"""Client↔controller plumbing for managed jobs.

Parity: sky/jobs/utils.py — the ManagedJobCodeGen twin (client executes
short python programs on the controller host over the command runner),
queue formatting, and dag-yaml (de)serialization (sky/utils/dag_utils).
"""
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu.podlet import codegen as podlet_codegen
from skypilot_tpu.task import Task

parse_result = podlet_codegen.parse_result

_IMPORTS = ('from skypilot_tpu.jobs import state as jobs_state\n'
            'from skypilot_tpu.jobs import constants as jobs_constants')


def _wrap(body: str) -> str:
    return podlet_codegen.wrap_python(body, _IMPORTS)


class ManagedJobCodeGen:
    """Shell commands to run on the controller host."""

    @staticmethod
    def get_queue() -> str:
        return _wrap('_emit(json.loads(jobs_state.queue_as_json()))\n')

    @staticmethod
    def cancel(job_ids: Optional[List[int]] = None,
               name: Optional[str] = None, all_jobs: bool = False) -> str:
        body = (
            f'ids = {job_ids!r}\n'
            f'name = {name!r}\n'
            f'if name is not None:\n'
            f'    ids = jobs_state.get_job_ids_by_name(name)\n'
            f'if {all_jobs!r}:\n'
            f'    ids = sorted({{r["job_id"] for r in '
            f'jobs_state.get_queue()}})\n'
            f'sigdir = os.path.expanduser(jobs_constants.SIGNAL_DIR)\n'
            f'os.makedirs(sigdir, exist_ok=True)\n'
            f'touched = []\n'
            f'for jid in (ids or []):\n'
            f'    st = jobs_state.get_status(jid)\n'
            f'    if st is not None and not st.is_terminal():\n'
            f'        open(os.path.join(sigdir, str(jid)), "w").write('
            f'"CANCEL")\n'
            f'        touched.append(jid)\n'
            f'_emit({{"cancelled": touched}})\n')
        return _wrap(body)

    @staticmethod
    def get_status(job_id: int) -> str:
        body = (f'st = jobs_state.get_status({job_id})\n'
                f'_emit({{"status": st.value if st else None}})\n')
        return _wrap(body)

    @staticmethod
    def tail_logs(job_id: Optional[int], follow: bool = True) -> str:
        """Streams the managed log file (raw output, no markers)."""
        body = (
            f'jid = {job_id!r}\n'
            f'if jid is None:\n'
            f'    rows = jobs_state.get_queue()\n'
            f'    jid = rows[0]["job_id"] if rows else None\n'
            f'if jid is None:\n'
            f'    sys.exit("No managed jobs.")\n'
            f'path = os.path.join(os.path.expanduser('
            f'jobs_constants.LOG_DIR), str(jid) + ".log")\n'
            f'pos = 0\n'
            f'quiet_after_done = 0\n'
            f'while True:\n'
            f'    chunk = ""\n'
            f'    if os.path.exists(path):\n'
            f'        with open(path, "r", errors="replace") as f:\n'
            f'            f.seek(pos)\n'
            f'            chunk = f.read()\n'
            f'            pos = f.tell()\n'
            f'        if chunk:\n'
            f'            sys.stdout.write(chunk); sys.stdout.flush()\n'
            f'    st = jobs_state.get_status(jid)\n'
            f'    done = st is not None and st.is_terminal()\n'
            # After the job is terminal the LogStreamer may still be
            # draining the cluster's run.log; keep reading until the file
            # has been quiet for a few polls.
            f'    if done and not chunk:\n'
            f'        quiet_after_done += 1\n'
            f'        if quiet_after_done >= 4 or not {follow!r}:\n'
            f'            break\n'
            f'    elif not {follow!r} and not done:\n'
            f'        break\n'
            f'    time.sleep(0.5)\n')
        return _wrap(body)


# ------------------------------------------------------------- dag yaml i/o


def sanitize_cluster_name(name: str) -> str:
    s = re.sub(r'[^a-z0-9-]', '-', name.lower()).strip('-')
    s = re.sub(r'-+', '-', s) or 'job'
    if not s[0].isalpha():
        s = 'j-' + s
    return s[:50].rstrip('-')


def dump_chain_dag_to_yaml(dag: dag_lib.Dag, path: str) -> None:
    """Multi-document YAML: doc 0 = {name}, then one doc per task in
    topological order (parity: sky/utils/dag_utils.py)."""
    import yaml
    configs: List[Dict[str, Any]] = [{'name': dag.name}]
    for task in dag.topological_order():
        configs.append(task.to_yaml_config())
    with open(path, 'w', encoding='utf-8') as f:
        yaml.safe_dump_all(configs, f, default_flow_style=False)


def load_chain_dag_from_yaml(path: str) -> dag_lib.Dag:
    import yaml
    with open(path, 'r', encoding='utf-8') as f:
        configs = list(yaml.safe_load_all(f))
    if not configs:
        raise exceptions.InvalidTaskError(f'Empty dag yaml: {path}')
    dag_name = None
    if set(configs[0].keys()) == {'name'}:
        dag_name = configs[0]['name']
        configs = configs[1:]
    with dag_lib.Dag(name=dag_name) as dag:
        prev: Optional[Task] = None
        for cfg in configs:
            task = Task.from_yaml_config(cfg)
            dag.add(task)
            if prev is not None:
                dag.add_edge(prev, task)
            prev = task
    return dag


def to_chain_dag(task_or_dag) -> dag_lib.Dag:
    if isinstance(task_or_dag, dag_lib.Dag):
        if not task_or_dag.is_chain():
            raise exceptions.NotSupportedError(
                'Managed jobs support single tasks and linear pipelines '
                'only.')
        return task_or_dag
    with dag_lib.Dag() as dag:
        dag.add(task_or_dag)
    dag.name = task_or_dag.name
    return dag


# ---------------------------------------------------------------- formatting


def format_job_queue(rows: List[Dict[str, Any]]) -> str:
    import time as time_lib
    header = (f'{"ID":<5}{"TASK":<6}{"NAME":<20}{"RESOURCES":<24}'
              f'{"SUBMITTED":<20}{"STATUS":<18}{"#RECOVERIES":<12}'
              f'{"CLUSTER"}')
    lines = [header]
    for r in rows:
        ts = r.get('job_submitted_at') or r.get('submitted_at')
        ts_s = (time_lib.strftime('%Y-%m-%d %H:%M:%S',
                                  time_lib.localtime(ts)) if ts else '-')
        lines.append(
            f'{r["job_id"]:<5}{r["task_id"]:<6}'
            f'{(r.get("job_name") or r.get("task_name") or "-")[:18]:<20}'
            f'{(r.get("resources") or "-")[:22]:<24}{ts_s:<20}'
            f'{r["status"]:<18}{r.get("recovery_count", 0):<12}'
            f'{r.get("cluster_name") or "-"}')
    return '\n'.join(lines)


def controller_envs() -> Dict[str, str]:
    """Env vars forwarded from client to controller task (test knobs)."""
    import os
    envs = {}
    for key in ('SKYTPU_JOBS_CHECK_GAP', 'SKYTPU_JOBS_STARTED_GAP',
                'SKYTPU_JOBS_RETRY_GAP'):
        if key in os.environ:
            envs[key] = os.environ[key]
    return envs
