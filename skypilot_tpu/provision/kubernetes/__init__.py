"""Kubernetes provisioning: one pod per TPU host, pods-as-hosts.

Parity: sky/provision/kubernetes/instance.py:921 (pods-as-nodes) —
TPU-first: a GKE TPU podslice is claimed by pods requesting the
`google.com/tpu` extended resource with the accelerator/topology
nodeSelectors the cloud layer mapped (clouds/kubernetes.gke_selectors).
GKE's TPU scheduler places the slice's pods onto the matching node
pool's hosts atomically — the same slice-atomic gang semantics the
TPU-VM path gets from tpu.googleapis.com.

All cluster interaction goes through the `kubectl` binary (the
reference delegates to binaries/SDKs the same way; the k8s python
client is not vendored).  `_kubectl` is the single seam tests fake.

Cluster layout on the k8s side (all labeled `skytpu/cluster=<name>`):
  - Pods  <cluster>-host{i}: `sleep infinity` + the runtime synced in
    by the provisioner (kubectl cp), podlet started by instance setup.
  - Headless Service <cluster>-svc: stable DNS for pod-to-pod
    rendezvous (`<pod>.<svc>.<ns>.svc.cluster.local`).

Multi-host note: the podlet driver fans out from the head pod over the
pod IPs recorded in ClusterInfo; images must carry python3 (default
image python:3.11-slim) — sshd is NOT required because intra-cluster
exec uses the pod network directly.
"""
import json
import subprocess
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions, logsys
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)
from skypilot_tpu.utils import command_runner

logger = logsys.init_logger(__name__)

LABEL = 'skytpu/cluster'
DEFAULT_IMAGE = 'python:3.11-slim'
_WAIT_TIMEOUT = 1800
# Pending + Unschedulable for this long = the k8s stockout (no node
# pool has capacity for the podslice) -> TpuStockoutError feeds the
# backend's zone blocklist failover.  Module-level so tests can shrink.
UNSCHEDULABLE_GRACE = 300


def _kubectl(args: List[str], stdin: Optional[str] = None,
             check: bool = True) -> subprocess.CompletedProcess:
    """Single seam for every cluster interaction (tests fake this)."""
    res = subprocess.run(['kubectl'] + args, input=stdin,
                         capture_output=True, text=True)
    if check and res.returncode != 0:
        raise exceptions.ProvisionError(
            f'kubectl {" ".join(args[:3])}... failed: '
            f'{res.stderr[-500:]}')
    return res


def _pod_name(cluster_name: str, i: int) -> str:
    return f'{cluster_name}-host{i}'


def _pod_manifest(cluster_name: str, i: int, config: Dict) -> Dict:
    selectors = dict(config.get('node_selectors') or {})
    if config.get('use_spot') and selectors:
        selectors['cloud.google.com/gke-spot'] = 'true'
    chips = int(config.get('chips_per_host') or 0)
    container: Dict = {
        'name': 'skytpu',
        'image': config.get('image') or DEFAULT_IMAGE,
        'command': ['/bin/sh', '-c', 'sleep infinity'],
    }
    if chips:
        container['resources'] = {
            'requests': {'google.com/tpu': str(chips)},
            'limits': {'google.com/tpu': str(chips)},
        }
    spec: Dict = {
        'restartPolicy': 'Never',
        'subdomain': f'{cluster_name}-svc',
        'hostname': _pod_name(cluster_name, i),
        'containers': [container],
    }
    if selectors:
        spec['nodeSelector'] = selectors
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': _pod_name(cluster_name, i),
            'labels': {LABEL: cluster_name,
                       'skytpu/rank': str(i)},
            # Slice metadata rides annotations so get_cluster_info can
            # reconstruct it from the cluster alone (parity with the
            # gcp provider persisting accelerator/chips in metadata).
            'annotations': {
                'skytpu/accelerator': str(config.get('accelerator')
                                          or ''),
                'skytpu/chips-per-host': str(chips),
            },
        },
        'spec': spec,
    }


def _service_manifest(cluster_name: str) -> Dict:
    return {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {
            'name': f'{cluster_name}-svc',
            'labels': {LABEL: cluster_name},
        },
        'spec': {
            'clusterIP': 'None',           # headless: per-pod DNS
            'selector': {LABEL: cluster_name},
        },
    }


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: Dict) -> ProvisionRecord:
    if int(config.get('num_slices', 1)) > 1:
        # Belt-and-braces behind the backend's MULTI_SLICE feasibility
        # gate: multislice (MEGASCALE over DCN) on GKE needs JobSet-style
        # slice grouping the pod-per-host layout cannot express.
        raise exceptions.ProvisionError(
            'kubernetes cannot gang-provision multiple podslices '
            f'(num_slices={config["num_slices"]}); use cloud: gcp',
            retryable=False)
    num_hosts = int(config.get('num_hosts', 1))
    existing = query_instances(cluster_name)
    if existing and all(s == 'running' for s in existing.values()):
        return ProvisionRecord('kubernetes', cluster_name, region, zone,
                               resource_id=cluster_name, is_resume=True)
    items = [_service_manifest(cluster_name)] + [
        _pod_manifest(cluster_name, i, config) for i in range(num_hosts)
    ]
    manifest = json.dumps({'apiVersion': 'v1', 'kind': 'List',
                           'items': items})
    _kubectl(['apply', '-f', '-'], stdin=manifest)
    logger.info('[k8s] applied %d pod(s) + service for %s', num_hosts,
                cluster_name)
    return ProvisionRecord('kubernetes', cluster_name, region, zone,
                           resource_id=cluster_name,
                           is_resume=bool(existing))


def _get_pods(cluster_name: str) -> List[Dict]:
    res = _kubectl(['get', 'pods', '-l', f'{LABEL}={cluster_name}',
                    '-o', 'json'])
    return json.loads(res.stdout).get('items', [])


def wait_instances(region: str, zone: Optional[str], cluster_name: str,
                   state: str = 'running') -> None:
    del region, zone
    if state != 'running':
        return
    start = time.time()
    deadline = start + _WAIT_TIMEOUT
    while time.time() < deadline:
        pods = _get_pods(cluster_name)
        phases = [p.get('status', {}).get('phase') for p in pods]
        if pods and all(ph == 'Running' for ph in phases):
            return
        if any(ph == 'Failed' for ph in phases):
            raise exceptions.ProvisionError(
                f'pod(s) of {cluster_name} failed: {phases}')
        # Unschedulable podslices surface as Pending with a
        # FailedScheduling condition — that is the k8s stockout.
        if time.time() - start >= UNSCHEDULABLE_GRACE:
            for p in pods:
                for cond in p.get('status', {}).get('conditions', []):
                    if cond.get('reason') == 'Unschedulable':
                        raise exceptions.TpuStockoutError(
                            f'{cluster_name}: unschedulable after '
                            f'{UNSCHEDULABLE_GRACE}s: '
                            f'{cond.get("message", "")[:200]}')
        time.sleep(5)
    raise exceptions.ProvisionError(
        f'{cluster_name}: pods not Running within {_WAIT_TIMEOUT}s')


def get_cluster_info(region: str, zone: Optional[str],
                     cluster_name: str) -> ClusterInfo:
    pods = _get_pods(cluster_name)
    if not pods:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    pods.sort(key=lambda p: int(
        p['metadata'].get('labels', {}).get('skytpu/rank', '0')))
    instances = [
        InstanceInfo(instance_id=p['metadata']['name'],
                     internal_ip=p.get('status', {}).get('podIP', ''),
                     external_ip=None)
        for p in pods
    ]
    anno = pods[0]['metadata'].get('annotations', {})
    return ClusterInfo(cluster_name=cluster_name,
                       provider='kubernetes',
                       region=region,
                       zone=zone,
                       instances=instances,
                       accelerator=anno.get('skytpu/accelerator') or None,
                       chips_per_host=int(
                           anno.get('skytpu/chips-per-host') or 0),
                       num_slices=1)


_PHASE_MAP = {
    'Pending': 'starting',
    'Running': 'running',
    'Succeeded': 'terminated',
    'Failed': 'terminated',
    'Unknown': 'stopped',
}


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    try:
        pods = _get_pods(cluster_name)
    except exceptions.ProvisionError:
        return {}
    return {
        p['metadata']['name']: _PHASE_MAP.get(
            p.get('status', {}).get('phase'), 'stopped')
        for p in pods
    }


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    raise exceptions.NotSupportedError(
        'kubernetes pods terminate, they do not stop; use down')


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    _kubectl(['delete', 'pods,services', '-l',
              f'{LABEL}={cluster_name}', '--ignore-not-found=true'],
             check=False)


def _expand_ports(ports: List[str]) -> List[int]:
    """'8080' and '10000-10010' specs (both legal per Resources
    validation) -> flat port list."""
    out: List[int] = []
    for p in ports:
        if '-' in str(p):
            lo, hi = str(p).split('-', 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(p))
    return out


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Optional[Dict] = None) -> None:
    """Expose ports via a NodePort service (LBs are cluster policy)."""
    svc = {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {
            'name': f'{cluster_name}-ports',
            'labels': {LABEL: cluster_name},
        },
        'spec': {
            'type': 'NodePort',
            'selector': {LABEL: cluster_name, 'skytpu/rank': '0'},
            'ports': [{'name': f'p{p}', 'port': p, 'targetPort': p}
                      for p in _expand_ports(ports)],
        },
    }
    _kubectl(['apply', '-f', '-'], stdin=json.dumps(svc))


def get_command_runners(
        cluster_info: ClusterInfo
) -> List[command_runner.CommandRunner]:
    return [
        command_runner.KubernetesPodRunner(inst.instance_id)
        for inst in cluster_info.instances
    ]
