"""Cloud-neutral provisioning orchestration.

Parity: sky/provision/provisioner.py — bulk_provision (create→wait with
cleanup-on-failure) and post_provision_runtime_setup (connection wait →
runtime sync → podlet start).  Differences for TPU-first design:

- no Ray bootstrap: the runtime is just the skypilot_tpu package rsynced to
  each host plus the podlet daemon on the head host;
- version lockstep is by content hash of the package tree (the reference
  builds/rsyncs a wheel, sky/backends/wheel_utils.py:136 — a hash-named
  rsync of the source tree achieves the same invariant with less machinery);
- idempotent per-host setup with a result cache, parity:
  _parallel_ssh_with_cache (sky/provision/instance_setup.py:108).
"""
import hashlib
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, logsys, native, provision
from skypilot_tpu.podlet import driver as driver_lib
from skypilot_tpu.provision.common import ClusterInfo, ProvisionRecord
from skypilot_tpu.provision.common import metadata_dir
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import common, subprocess_utils, timeline

logger = logsys.init_logger(__name__)

_RUNTIME_DIR = '~/.skytpu_runtime'


@timeline.event
def bulk_provision(provider: str, region: str, zone: Optional[str],
                   cluster_name: str, config: Dict[str, Any],
                   log_path: str) -> ProvisionRecord:
    """One provisioning attempt: create + wait; cleanup on failure.
    Parity: sky/provision/provisioner.py:44-196."""
    try:
        record = provision.run_instances(provider, region, zone, cluster_name,
                                         config)
        provision.wait_instances(provider, region, zone, cluster_name)
        return record
    except (exceptions.ProvisionError, exceptions.ApiError):
        # Leave no half-created slice behind: stockout handling must see a
        # clean zone on the next attempt.
        try:
            provision.terminate_instances(provider, cluster_name)
        except Exception as cleanup_err:  # pylint: disable=broad-except
            logger.warning('Cleanup after failed provision also failed: %s',
                           cleanup_err)
        raise


def _package_root() -> str:
    import skypilot_tpu
    return os.path.dirname(os.path.abspath(skypilot_tpu.__file__))


def runtime_tree_hash() -> str:
    """Content hash of the framework package (version-lockstep token)."""
    root = _package_root()
    h = hashlib.md5()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames[:] = sorted(d for d in dirnames if d != '__pycache__')
        for fn in sorted(filenames):
            if fn.endswith(('.pyc', '.lock')):
                continue
            path = os.path.join(dirpath, fn)
            h.update(os.path.relpath(path, root).encode())
            with open(path, 'rb') as f:
                h.update(f.read())
    return h.hexdigest()[:16]


def _setup_cache_path(cluster_name: str, node_id: str, step: str) -> str:
    safe = node_id.replace('/', '_')
    return os.path.join(metadata_dir(cluster_name), f'setup-{safe}-{step}')


def _cached(cluster_name: str, node_id: str, step: str, token: str) -> bool:
    try:
        with open(_setup_cache_path(cluster_name, node_id, step), 'r',
                  encoding='utf-8') as f:
            return f.read().strip() == token
    except FileNotFoundError:
        return False


def _mark(cluster_name: str, node_id: str, step: str, token: str) -> None:
    with open(_setup_cache_path(cluster_name, node_id, step), 'w',
              encoding='utf-8') as f:
        f.write(token)


@timeline.event
def post_provision_runtime_setup(cluster_name: str, cluster_info: ClusterInfo,
                                 log_path: str) -> None:
    """Make a freshly-created (or resumed) cluster runnable:

    1. wait until every host answers;
    2. rsync the framework package to every host (hash-cached);
    3. write cluster_info.json + ssh key to the head host;
    4. start/restart the podlet daemon on the head host.
    """
    runners = provision.get_command_runners(cluster_info.provider,
                                            cluster_info)
    runner_lib.wait_for_connection(runners)

    token = runtime_tree_hash()
    pkg_root = _package_root()

    def _sync_runtime(i: int) -> None:
        runner = runners[i]
        if _cached(cluster_name, runner.node_id, 'runtime', token):
            return
        runner.run(f'mkdir -p {_RUNTIME_DIR} ~/.skytpu', log_path=log_path)
        runner.rsync(pkg_root + '/', f'{_RUNTIME_DIR}/skypilot_tpu/',
                     up=True, log_path=log_path)
        # Compile the native job supervisor on the host (idempotent per
        # source hash; a compiler-less host just uses the shell fallback).
        if cluster_info.provider != 'local':
            runner.run(native.host_build_script(), log_path=log_path)
        _mark(cluster_name, runner.node_id, 'runtime', token)

    subprocess_utils.run_in_parallel(_sync_runtime, list(range(len(runners))))

    if cluster_info.provider == 'kubernetes' and len(runners) > 1:
        # Multi-host podslice: pods carry no sshd, so the head-pod gang
        # driver reaches workers over the podlet agent (podlet/agent.py)
        # — start one per worker pod, authed by a per-cluster token.
        _setup_pod_agents(cluster_name, cluster_info, runners, token,
                          log_path)

    # Head host extras: cluster info (for the gang driver + autostop) and
    # the private key so the head can reach workers over internal IPs.
    head = runners[0]
    info_for_head = cluster_info
    if cluster_info.provider == 'local':
        info_for_head.custom['skytpu_home'] = common.home_dir()
    info_json = info_for_head.to_json()
    local_tmp = os.path.join(metadata_dir(cluster_name), 'cluster_info.json')
    with open(local_tmp, 'w', encoding='utf-8') as f:
        f.write(info_json)
    head.rsync(local_tmp, driver_lib.CLUSTER_INFO_PATH, up=True,
               log_path=log_path)
    if cluster_info.provider != 'local' and cluster_info.ssh_private_key:
        head.run('mkdir -p ~/.ssh && chmod 700 ~/.ssh', log_path=log_path)
        head.rsync(cluster_info.ssh_private_key, '~/.ssh/skytpu-key', up=True,
                   log_path=log_path)
        head.run('chmod 600 ~/.ssh/skytpu-key', log_path=log_path)
        # Provider metadata (e.g. gcp.json with project/zone/resource id) so
        # the head host can tear down its own slice on autodown.  The head's
        # SKYTPU_HOME defaults to ~/.skytpu, so the path layout matches.
        meta_file = os.path.join(metadata_dir(cluster_name),
                                 f'{cluster_info.provider}.json')
        if os.path.exists(meta_file):
            head.run(f'mkdir -p ~/.skytpu/clusters/{cluster_name}',
                     log_path=log_path)
            head.rsync(meta_file,
                       f'~/.skytpu/clusters/{cluster_name}/'
                       f'{cluster_info.provider}.json',
                       up=True, log_path=log_path)

    _start_podlet(cluster_name, head, token, log_path)


def _agent_token(cluster_name: str) -> str:
    """Per-cluster agent auth token, persisted so resumes reuse it."""
    path = os.path.join(metadata_dir(cluster_name), 'agent_token')
    try:
        with open(path, 'r', encoding='utf-8') as f:
            return f.read().strip()
    except FileNotFoundError:
        import secrets
        tok = secrets.token_hex(16)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(tok)
        return tok


def _setup_pod_agents(cluster_name: str, cluster_info: ClusterInfo,
                      runners: List[runner_lib.CommandRunner],
                      version_token: str, log_path: str) -> None:
    """Write the auth token to every pod and (re)start the exec agent on
    the worker pods (rank >= 1); the head pod needs no agent — the
    driver runs there.  Idempotent + version-gated like the podlet."""
    from skypilot_tpu.podlet.agent import AGENT_PORT_BASE
    agent_token = _agent_token(cluster_name)

    def _one(rank: int) -> None:
        runner = runners[rank]
        import shlex
        runner.run_or_raise(
            'mkdir -p ~/.skytpu && umask 077 && '
            f'printf %s {shlex.quote(agent_token)} > ~/.skytpu/agent_token',
            log_path=log_path)
        if rank == 0:
            return
        port = AGENT_PORT_BASE + rank
        # The version token is recorded ONLY after a successful connect
        # check (below): a fire-and-forget nohup always exits 0, and a
        # bind/startup failure stamped as "current" would never be
        # retried — it would surface days later as an opaque job error.
        check_and_start = (
            f'export PYTHONPATH={_RUNTIME_DIR}:$PYTHONPATH; '
            f'mkdir -p ~/.skytpu/agent; '
            f'CUR=$(cat ~/.skytpu/agent/version.token 2>/dev/null '
            f'|| echo none); '
            f'PID=$(cat ~/.skytpu/agent/pid 2>/dev/null || true); '
            f'ALIVE=no; '
            f'if [ -n "$PID" ] && kill -0 "$PID" 2>/dev/null; '
            f'then ALIVE=yes; fi; '
            f'if [ "$CUR" != "{version_token}" ] || [ "$ALIVE" != yes ]; '
            f'then '
            f'  if [ -n "$PID" ]; then kill "$PID" 2>/dev/null || true; fi; '
            f'  rm -f ~/.skytpu/agent/version.token; '
            f'  nohup python3 -m skypilot_tpu.podlet.agent --port {port} '
            f'    >> ~/.skytpu/agent/agent.log 2>&1 & '
            f'  echo $! > ~/.skytpu/agent/pid; '
            f'fi')
        runner.run_or_raise(check_and_start, log_path=log_path)
        # Connect check runs ON the pod (pod IPs are cluster-internal —
        # the client cannot reach them directly).
        import time
        ping = ('python3 -c \'import socket; '
                f'socket.create_connection(("127.0.0.1", {port}), '
                '2).close()\'')
        deadline = time.time() + 60
        while True:
            if runner.run(ping, log_path=log_path) == 0:
                break
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'podlet agent on {runner.node_id} did not come up '
                    f'on port {port} within 60s — see '
                    '~/.skytpu/agent/agent.log on the pod',
                    retryable=False)
            time.sleep(2)
        runner.run_or_raise(
            f'echo {version_token} > ~/.skytpu/agent/version.token',
            log_path=log_path)

    subprocess_utils.run_in_parallel(_one, list(range(len(runners))))
    cluster_info.custom['agent_token'] = agent_token
    cluster_info.custom['agent_port_base'] = AGENT_PORT_BASE


def _start_podlet(cluster_name: str, head: runner_lib.CommandRunner,
                  token: str, log_path: str) -> None:
    """(Re)start the podlet daemon if missing or version-stale.
    Parity: start_skylet_on_head_node + attempt_skylet restart-if-changed."""
    env_exports = ''
    if isinstance(head, runner_lib.LocalProcessRunner):
        # Local cloud: the daemon needs the client state root for autostop.
        env_exports = f'export SKYTPU_HOME={common.home_dir()}; '
    check_and_start = (
        f'{env_exports}'
        f'export PYTHONPATH={_RUNTIME_DIR}:$PYTHONPATH; '
        f'mkdir -p ~/.skytpu/podlet; '
        f'CUR=$(cat ~/.skytpu/podlet/version.token 2>/dev/null || echo none); '
        f'PID=$(cat ~/.skytpu/podlet/pid 2>/dev/null || true); '
        f'ALIVE=no; '
        # kill -0 alone counts ZOMBIES as alive: a nohup-orphaned daemon
        # that exited (autostop) but has not been reaped by pid 1 yet
        # still sits in the process table, and a stop->resume would then
        # skip the restart, leaving the cluster daemon-less (jobs pend
        # forever).  Alive = ps reports a non-empty, non-Z state (an
        # empty stat means gone/reaped mid-probe — dead, not alive).
        f'if [ -n "$PID" ]; then '
        f'STAT=$(ps -o stat= -p "$PID" 2>/dev/null | tr -d \' \'); '
        f'case "$STAT" in ""|Z*) ;; *) ALIVE=yes ;; esac; fi; '
        f'if [ "$CUR" != "{token}" ] || [ "$ALIVE" != yes ]; then '
        f'  if [ -n "$PID" ]; then kill "$PID" 2>/dev/null || true; fi; '
        f'  nohup python3 -m skypilot_tpu.podlet.daemon '
        f'    >> ~/.skytpu/podlet/daemon.log 2>&1 & '
        f'  echo {token} > ~/.skytpu/podlet/version.token; '
        f'fi')
    head.run_or_raise(check_and_start, log_path=log_path)


def teardown_cluster(provider: str, cluster_name: str,
                     terminate: bool) -> None:
    if terminate:
        provision.terminate_instances(provider, cluster_name)
        # Drop the idempotency cache so a future same-name cluster re-syncs.
        import shutil
        shutil.rmtree(metadata_dir(cluster_name), ignore_errors=True)
    else:
        provision.stop_instances(provider, cluster_name)
