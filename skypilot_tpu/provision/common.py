"""Shared provisioning data structures.

Parity: sky/provision/common.py (ProvisionRecord, ClusterInfo, InstanceInfo).
A TPU slice provisions as ONE cloud resource that yields MANY hosts; these
structs model that directly (instances == hosts).
"""
import dataclasses
import json
import os
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class InstanceInfo:
    """One host (TPU-VM worker, controller VM, or local host dir)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    ssh_port: int = 22
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Local cloud only: the host's directory.
    local_dir: Optional[str] = None


@dataclasses.dataclass
class ClusterInfo:
    """Everything the backend needs to reach a provisioned cluster."""
    cluster_name: str
    provider: str                      # 'gcp' | 'local'
    region: str
    zone: Optional[str]
    instances: List[InstanceInfo]      # host 0 is the head host
    ssh_user: str = ''
    ssh_private_key: str = ''
    docker_user: Optional[str] = None
    # Slice-level metadata (None for plain VMs).
    accelerator: Optional[str] = None
    chips_per_host: int = 0
    num_slices: int = 1
    custom: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def head(self) -> InstanceInfo:
        return self.instances[0]

    @property
    def num_hosts(self) -> int:
        return len(self.instances)

    @property
    def hosts_per_slice(self) -> int:
        """Hosts in each slice; instances are ordered slice-major, so host
        i belongs to slice i // hosts_per_slice."""
        return max(len(self.instances) // max(self.num_slices, 1), 1)

    def internal_ips(self) -> List[str]:
        return [i.internal_ip for i in self.instances]

    def external_ips(self) -> List[str]:
        return [i.external_ip or i.internal_ip for i in self.instances]

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        return json.dumps(d, indent=2)

    @classmethod
    def from_json(cls, s: str) -> 'ClusterInfo':
        d = json.loads(s)
        d['instances'] = [InstanceInfo(**i) for i in d['instances']]
        return cls(**d)


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances for one attempt."""
    provider: str
    cluster_name: str
    region: str
    zone: Optional[str]
    resource_id: str                   # TPU node name / instance group id
    is_resume: bool = False


def metadata_dir(cluster_name: str) -> str:
    from skypilot_tpu.utils import common
    d = os.path.join(common.home_dir(), 'clusters', cluster_name)
    os.makedirs(d, exist_ok=True)
    return d
