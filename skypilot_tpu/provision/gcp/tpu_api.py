"""Thin REST client for tpu.googleapis.com (v2) — TPU-VM slices.

Parity: sky/provision/gcp/instance_utils.py:1185-1651 (GCPTPUVMInstance) —
re-designed: the reference drives TPUs through googleapiclient discovery and
treats them as a special node type inside a VM provisioner; here the slice
is the only first-class object, talked to over plain REST (requests +
google-auth), including the queued-resources API for spot/reserved capacity.

Request *construction* is pure (unit-testable without credentials); only
``_call`` touches the network.
"""
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, logsys

logger = logsys.init_logger(__name__)

_TPU_API = 'https://tpu.googleapis.com/v2'
_OP_POLL_INTERVAL = 5.0

# -------------------------------------------------------------- auth layer


def _get_session():
    """Authorized requests session via application-default credentials."""
    import google.auth
    import google.auth.transport.requests
    creds, _ = google.auth.default(
        scopes=['https://www.googleapis.com/auth/cloud-platform'])
    session = google.auth.transport.requests.AuthorizedSession(creds)
    return session


def _call(method: str, url: str, json_body: Optional[Dict] = None,
          session=None) -> Dict[str, Any]:
    session = session or _get_session()
    resp = session.request(method, url, json=json_body)
    if resp.status_code >= 400:
        raise classify_http_error(resp.status_code, resp.text)
    if not resp.text:
        return {}
    return resp.json()


# -------------------------------------------------- error classification


def classify_http_error(status: int, text: str) -> Exception:
    """Map a TPU API error to the failover taxonomy.

    Parity: the reference's GCP handler distinguishes quota vs capacity vs
    config errors (sky/backends/cloud_vm_ray_backend.py:946 _gcp_handler,
    TPU_NODE_CREATION_FAILURE in sky/provision/gcp/instance_utils.py:26).
    Stockout must NOT be retried in the same zone; quota must skip the whole
    region/project; config errors must abort failover entirely.
    """
    lower = text.lower()
    stockout_markers = (
        'there is no more capacity', 'not enough resources',
        'does not have enough resources', 'resource_exhausted', 'stockout',
        'no available capacity', 'out of capacity', 'insufficient capacity',
        'resource pool exhausted',
    )
    quota_markers = ('quota', 'rate limit')
    if status == 429 or any(m in lower for m in stockout_markers):
        return exceptions.TpuStockoutError(f'TPU capacity error: {text[:400]}')
    if status == 403 and any(m in lower for m in quota_markers):
        return exceptions.QuotaExceededError(f'TPU quota error: {text[:400]}')
    if status in (400, 404, 409):
        return exceptions.ProvisionError(
            f'TPU API error {status}: {text[:400]}', retryable=False)
    return exceptions.ApiError(f'TPU API error {status}: {text[:400]}')


# ----------------------------------------------------- request construction


def node_url(project: str, zone: str, node_id: str = '') -> str:
    base = f'{_TPU_API}/projects/{project}/locations/{zone}/nodes'
    return f'{base}/{node_id}' if node_id else base


def queued_resource_url(project: str, zone: str, qr_id: str = '') -> str:
    base = f'{_TPU_API}/projects/{project}/locations/{zone}/queuedResources'
    return f'{base}/{qr_id}' if qr_id else base


def build_node_body(
    *,
    accelerator_type: str,           # GCP style, e.g. 'v5litepod-16'
    runtime_version: str,
    ssh_public_key: str,
    ssh_user: str,
    use_spot: bool = False,
    reservation: Optional[str] = None,
    network: Optional[str] = None,
    subnetwork: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    startup_script: Optional[str] = None,
    tags: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Node create body (pure)."""
    body: Dict[str, Any] = {
        'acceleratorType': accelerator_type,
        'runtimeVersion': runtime_version,
        'networkConfig': {
            'network': network or 'default',
            'subnetwork': subnetwork or '',
            'enableExternalIps': True,
        },
        'metadata': {
            'ssh-keys': f'{ssh_user}:{ssh_public_key}',
        },
        'labels': dict(labels or {}),
        'tags': list(tags or ['skytpu']),
    }
    if not body['networkConfig']['subnetwork']:
        del body['networkConfig']['subnetwork']
    if startup_script:
        body['metadata']['startup-script'] = startup_script
    if use_spot:
        body['schedulingConfig'] = {'spot': True}
    if reservation:
        body['schedulingConfig'] = {
            'reserved': True,
        }
        body['reservationName'] = reservation
    return body


def build_queued_resource_body(node_id: str, node_body: Dict[str, Any],
                               use_spot: bool,
                               valid_until_seconds: Optional[int] = None
                               ) -> Dict[str, Any]:
    """Queued-resource wrapper for capacity that may take long to obtain."""
    node = dict(node_body)
    node.pop('schedulingConfig', None)
    body: Dict[str, Any] = {
        'tpu': {
            'nodeSpec': [{
                'nodeId': node_id,
                'node': node,
            }]
        },
    }
    if use_spot:
        body['spot'] = {}
    else:
        body['guaranteed'] = {}
    if valid_until_seconds:
        body['queueingPolicy'] = {
            'validUntilDuration': {'seconds': valid_until_seconds}
        }
    return body


# ----------------------------------------------------------- API operations


def create_node(project: str, zone: str, node_id: str,
                body: Dict[str, Any], session=None) -> Dict[str, Any]:
    url = node_url(project, zone) + f'?nodeId={node_id}'
    op = _call('POST', url, body, session=session)
    return wait_operation(op, session=session)


def create_queued_resource(project: str, zone: str, qr_id: str,
                           body: Dict[str, Any], session=None
                           ) -> Dict[str, Any]:
    url = queued_resource_url(project, zone) + f'?queuedResourceId={qr_id}'
    return _call('POST', url, body, session=session)


def get_node(project: str, zone: str, node_id: str,
             session=None) -> Optional[Dict[str, Any]]:
    try:
        return _call('GET', node_url(project, zone, node_id), session=session)
    except exceptions.ProvisionError as e:
        if '404' in str(e):
            return None
        raise


def list_nodes(project: str, zone: str, session=None) -> List[Dict[str, Any]]:
    out = _call('GET', node_url(project, zone), session=session)
    return out.get('nodes', [])


def delete_node(project: str, zone: str, node_id: str, session=None) -> None:
    try:
        op = _call('DELETE', node_url(project, zone, node_id), session=session)
    except exceptions.ProvisionError as e:
        if '404' in str(e):
            return
        raise
    wait_operation(op, session=session)


def delete_queued_resource(project: str, zone: str, qr_id: str,
                           session=None) -> None:
    try:
        _call('DELETE',
              queued_resource_url(project, zone, qr_id) + '?force=true',
              session=session)
    except exceptions.ProvisionError as e:
        if '404' not in str(e):
            raise


def wait_operation(op: Dict[str, Any], timeout: float = 1800,
                   session=None) -> Dict[str, Any]:
    """Poll a long-running TPU operation until done.
    Parity: TPU op polling (sky/provision/gcp/instance_utils.py:1211)."""
    if 'name' not in op or op.get('done'):
        return op.get('response', op)
    url = f'https://tpu.googleapis.com/v2/{op["name"]}'
    deadline = time.time() + timeout
    session = session or _get_session()
    while time.time() < deadline:
        cur = _call('GET', url, session=session)
        if cur.get('done'):
            if 'error' in cur:
                err = cur['error']
                raise classify_http_error(
                    int(err.get('code', 500)), err.get('message', str(err)))
            return cur.get('response', cur)
        time.sleep(_OP_POLL_INTERVAL)
    raise exceptions.ApiError(f'TPU operation timed out: {op.get("name")}')


def wait_node_ready(project: str, zone: str, node_id: str,
                    timeout: float = 1800, session=None) -> Dict[str, Any]:
    deadline = time.time() + timeout
    session = session or _get_session()
    while time.time() < deadline:
        node = get_node(project, zone, node_id, session=session)
        state = (node or {}).get('state')
        if state == 'READY':
            return node
        if state in ('PREEMPTED', 'TERMINATED', 'FAILED'):
            raise exceptions.ProvisionError(
                f'TPU node {node_id} entered state {state}', retryable=True)
        time.sleep(_OP_POLL_INTERVAL)
    raise exceptions.ApiError(f'TPU node {node_id} not READY in {timeout}s')


def node_endpoints(node: Dict[str, Any]) -> List[Dict[str, Optional[str]]]:
    """[(internal_ip, external_ip)] per host, in worker order."""
    out = []
    for ep in node.get('networkEndpoints', []):
        external = None
        access = ep.get('accessConfig') or {}
        external = access.get('externalIp')
        out.append({'internal': ep.get('ipAddress'), 'external': external})
    return out
