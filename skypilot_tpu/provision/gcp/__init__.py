"""GCP provider: TPU slices (tpu_api) + controller VMs (compute_api).

Implements the provision-op interface (see provision/__init__.py).  The
deploy ``config`` dict comes from clouds.gcp.GCP.make_deploy_variables.

State model: the cloud is the source of truth (no local instance cache);
``metadata.json`` under the cluster metadata dir records only what we
created (node id / vm name, kind, project, zone) so terminate/query can
find it again — parity with the reference's tag-based discovery, using
labels instead (all resources carry label skytpu-cluster=<name>).
"""
import json
import os
from typing import Dict, List, Optional

from skypilot_tpu import authentication, exceptions, logsys
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)
from skypilot_tpu.provision.gcp import compute_api, tpu_api
from skypilot_tpu.utils import command_runner

logger = logsys.init_logger(__name__)


def _meta_path(cluster_name: str) -> str:
    return os.path.join(provision_common.metadata_dir(cluster_name),
                        'gcp.json')


def _save_meta(cluster_name: str, meta: Dict) -> None:
    with open(_meta_path(cluster_name), 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=2)


def _load_meta(cluster_name: str) -> Optional[Dict]:
    try:
        with open(_meta_path(cluster_name), 'r', encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _resource_name(cluster_name: str) -> str:
    return f'skytpu-{cluster_name}'


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: Dict) -> ProvisionRecord:
    assert zone is not None, 'GCP provisioning is zone-granular'
    project = config.get('project_id')
    if not project:
        raise exceptions.ProvisionError(
            'No GCP project configured (gcp.project_id).', retryable=False)
    name = _resource_name(cluster_name)
    ssh_user = authentication.default_ssh_user()
    pubkey = authentication.public_key_openssh()
    labels = dict(config.get('labels') or {})
    labels['skytpu-cluster'] = cluster_name

    if config['node_kind'] == 'tpu_slice':
        # Multi-slice: num_slices separate TPU resources, one per slice
        # (DCN connects them; ICI stays within each slice).  Names are
        # <base> for a single slice, <base>-s<i> otherwise.
        num_slices = int(config.get('num_slices', 1))
        names = ([name] if num_slices == 1 else
                 [f'{name}-s{i}' for i in range(num_slices)])
        # Save metadata BEFORE creating anything: if slice k of N fails
        # (stockout is the dominant TPU failure), the already-created
        # slices must stay tracked so failover cleanup / terminate can
        # delete them instead of leaking billed TPUs.
        _save_meta(
            cluster_name, {
                'kind': 'tpu_slice',
                'project': project,
                'zone': zone,
                'region': region,
                'resource_id': names[0],
                'resource_ids': names,
                'queued_resource': bool(config.get('queued_resource')),
                'accelerator': config.get('accelerator'),
                'chips_per_host': config.get('chips_per_host', 0),
                'ssh_user': ssh_user,
            })
        all_resumed = True
        for node_name in names:
            existing = tpu_api.get_node(project, zone, node_name)
            if existing is not None and existing.get('state') == 'READY':
                continue
            all_resumed = False
            if existing is not None:
                # Half-dead slice (e.g. PREEMPTED remnant): delete first —
                # TPU slices cannot be repaired in place.
                tpu_api.delete_node(project, zone, node_name)
            body = tpu_api.build_node_body(
                accelerator_type=config['tpu_type'],
                runtime_version=config['runtime_version'],
                ssh_public_key=pubkey,
                ssh_user=ssh_user,
                use_spot=config.get('use_spot', False),
                reservation=config.get('reservation'),
                network=config.get('network'),
                subnetwork=config.get('subnetwork'),
                labels=labels,
            )
            if config.get('queued_resource'):
                qr_body = tpu_api.build_queued_resource_body(
                    node_name, body, config.get('use_spot', False))
                tpu_api.create_queued_resource(project, zone, node_name,
                                               qr_body)
            else:
                tpu_api.create_node(project, zone, node_name, body)
        return ProvisionRecord('gcp', cluster_name, region, zone,
                               resource_id=names[0],
                               is_resume=all_resumed)

    # Plain VM (controllers).
    if int(config.get('num_slices', 1)) > 1:
        raise exceptions.ProvisionError(
            'num_nodes > 1 is only supported for TPU slice tasks; plain '
            'VM gangs are not implemented.', retryable=False)
    existing = compute_api.get_instance(project, zone, name)
    if existing is not None:
        # Resume: any non-running state (TERMINATED == stopped in GCE,
        # SUSPENDED, STOPPING) needs an explicit start to come back up.
        if existing.get('status') != 'RUNNING':
            compute_api.start_instance(project, zone, name)
        is_resume = True
    else:
        body = compute_api.build_instance_body(
            name=name,
            machine_type=config['instance_type'],
            zone=zone,
            ssh_user=ssh_user,
            ssh_public_key=pubkey,
            disk_size_gb=config.get('disk_size', 256),
            image=config.get('image_id'),
            use_spot=config.get('use_spot', False),
            labels=labels,
        )
        compute_api.create_instance(project, zone, body)
        is_resume = False
    _save_meta(
        cluster_name, {
            'kind': 'vm',
            'project': project,
            'zone': zone,
            'region': region,
            'resource_id': name,
            'ssh_user': ssh_user,
        })
    return ProvisionRecord('gcp', cluster_name, region, zone,
                           resource_id=name, is_resume=is_resume)


def wait_instances(region: str, zone: Optional[str], cluster_name: str,
                   state: str = 'running') -> None:
    del region
    meta = _load_meta(cluster_name)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    if meta['kind'] == 'tpu_slice':
        for node_name in _slice_ids(meta):
            tpu_api.wait_node_ready(meta['project'], meta['zone'],
                                    node_name)


def _slice_ids(meta: Dict) -> List[str]:
    """Slice resource names, oldest-metadata compatible."""
    return meta.get('resource_ids') or [meta['resource_id']]


def get_cluster_info(region: str, zone: Optional[str],
                     cluster_name: str) -> ClusterInfo:
    meta = _load_meta(cluster_name)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    project = meta['project']
    private_key, _ = authentication.get_key_paths()
    if meta['kind'] == 'tpu_slice':
        slice_ids = _slice_ids(meta)
        instances = []
        for s, node_name in enumerate(slice_ids):
            node = tpu_api.get_node(project, meta['zone'], node_name)
            if node is None:
                raise exceptions.ClusterDoesNotExist(
                    f'{cluster_name} (slice {node_name})')
            for i, ep in enumerate(tpu_api.node_endpoints(node)):
                instances.append(
                    InstanceInfo(
                        instance_id=f'{node_name}-w{i}',
                        internal_ip=ep['internal'] or '',
                        external_ip=ep['external'],
                        tags={'slice': str(s)},
                    ))
        return ClusterInfo(cluster_name=cluster_name,
                           provider='gcp',
                           region=meta['region'],
                           zone=meta['zone'],
                           instances=instances,
                           ssh_user=meta['ssh_user'],
                           ssh_private_key=private_key,
                           accelerator=meta.get('accelerator'),
                           chips_per_host=meta.get('chips_per_host', 0),
                           num_slices=len(slice_ids))
    inst = compute_api.get_instance(project, meta['zone'],
                                    meta['resource_id'])
    if inst is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    internal, external = compute_api.instance_ips(inst)
    return ClusterInfo(cluster_name=cluster_name,
                       provider='gcp',
                       region=meta['region'],
                       zone=meta['zone'],
                       instances=[
                           InstanceInfo(instance_id=meta['resource_id'],
                                        internal_ip=internal or '',
                                        external_ip=external)
                       ],
                       ssh_user=meta['ssh_user'],
                       ssh_private_key=private_key)


_TPU_STATE_MAP = {
    'READY': 'running',
    'CREATING': 'pending',
    'STARTING': 'pending',
    'REPAIRING': 'pending',
    'STOPPED': 'stopped',
    'STOPPING': 'stopped',
    'PREEMPTED': 'terminated',
    'TERMINATED': 'terminated',
    'DELETING': 'terminated',
    'FAILED': 'terminated',
}
_VM_STATE_MAP = {
    'RUNNING': 'running',
    'PROVISIONING': 'pending',
    'STAGING': 'pending',
    'STOPPING': 'stopped',
    'TERMINATED': 'stopped',   # GCE 'TERMINATED' == stopped-but-resumable
    'SUSPENDED': 'stopped',
}


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    meta = _load_meta(cluster_name)
    if meta is None:
        return {}
    project = meta['project']
    if meta['kind'] == 'tpu_slice':
        out: Dict[str, str] = {}
        for node_name in _slice_ids(meta):
            node = tpu_api.get_node(project, meta['zone'], node_name)
            if node is None:
                continue
            status = _TPU_STATE_MAP.get(node.get('state', ''), 'unknown')
            n_hosts = max(len(node.get('networkEndpoints', [])), 1)
            out.update(
                {f'{node_name}-w{i}': status for i in range(n_hosts)})
        return out
    inst = compute_api.get_instance(project, meta['zone'],
                                    meta['resource_id'])
    if inst is None:
        return {}
    return {
        meta['resource_id']:
            _VM_STATE_MAP.get(inst.get('status', ''), 'unknown')
    }


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    meta = _load_meta(cluster_name)
    if meta is None:
        return
    if meta['kind'] == 'tpu_slice':
        raise exceptions.NotSupportedError(
            'TPU slices cannot be stopped; terminate instead.')
    compute_api.stop_instance(meta['project'], meta['zone'],
                              meta['resource_id'])


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    meta = _load_meta(cluster_name)
    if meta is None:
        return
    if meta['kind'] == 'tpu_slice':
        for node_name in _slice_ids(meta):
            if meta.get('queued_resource'):
                tpu_api.delete_queued_resource(meta['project'],
                                               meta['zone'], node_name)
            tpu_api.delete_node(meta['project'], meta['zone'], node_name)
    else:
        compute_api.delete_instance(meta['project'], meta['zone'],
                                    meta['resource_id'])
    try:
        os.remove(_meta_path(cluster_name))
    except FileNotFoundError:
        pass


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Optional[Dict] = None) -> None:
    meta = _load_meta(cluster_name)
    if meta is None:
        return
    compute_api.open_firewall_ports(meta['project'], ports)


def get_command_runners(
        cluster_info: ClusterInfo
) -> List[command_runner.CommandRunner]:
    return [
        command_runner.SSHCommandRunner(
            ip=inst.external_ip or inst.internal_ip,
            ssh_user=cluster_info.ssh_user,
            ssh_private_key=cluster_info.ssh_private_key,
            port=inst.ssh_port,
        ) for inst in cluster_info.instances
    ]
