"""Thin REST client for compute.googleapis.com — controller CPU VMs.

Parity role: sky/provision/gcp/instance.py + config.py for plain VMs,
reduced to what the jobs/serve controller planes need (single VM, default
network, debian image, ssh-keys metadata, firewall for opened ports).
"""
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, logsys
from skypilot_tpu.provision.gcp import tpu_api

logger = logsys.init_logger(__name__)

_COMPUTE_API = 'https://compute.googleapis.com/compute/v1'
_DEFAULT_IMAGE = ('projects/debian-cloud/global/images/family/debian-12')


def instance_url(project: str, zone: str, name: str = '') -> str:
    base = f'{_COMPUTE_API}/projects/{project}/zones/{zone}/instances'
    return f'{base}/{name}' if name else base


def build_instance_body(
    *,
    name: str,
    machine_type: str,
    zone: str,
    ssh_user: str,
    ssh_public_key: str,
    disk_size_gb: int = 256,
    image: Optional[str] = None,
    use_spot: bool = False,
    labels: Optional[Dict[str, str]] = None,
    startup_script: Optional[str] = None,
) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/{zone}/machineTypes/{machine_type}',
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': image or _DEFAULT_IMAGE,
                'diskSizeGb': str(disk_size_gb),
            },
        }],
        'networkInterfaces': [{
            'network': 'global/networks/default',
            'accessConfigs': [{
                'name': 'External NAT',
                'type': 'ONE_TO_ONE_NAT'
            }],
        }],
        'metadata': {
            'items': [{
                'key': 'ssh-keys',
                'value': f'{ssh_user}:{ssh_public_key}'
            }] + ([{
                'key': 'startup-script',
                'value': startup_script
            }] if startup_script else []),
        },
        'labels': dict(labels or {}),
        'tags': {'items': ['skytpu']},
    }
    if use_spot:
        body['scheduling'] = {
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'STOP',
        }
    return body


def _wait_zone_op(project: str, zone: str, op: Dict[str, Any],
                  timeout: float = 600, session=None) -> None:
    name = op.get('name')
    if name is None:
        return
    url = (f'{_COMPUTE_API}/projects/{project}/zones/{zone}/operations/'
           f'{name}/wait')
    deadline = time.time() + timeout
    session = session or tpu_api._get_session()  # pylint: disable=protected-access
    while time.time() < deadline:
        cur = tpu_api._call('POST', url, session=session)  # pylint: disable=protected-access
        if cur.get('status') == 'DONE':
            if 'error' in cur:
                raise classify_zone_op_error(cur['error'].get('errors', []))
            return
    raise exceptions.ApiError(f'Compute operation timed out: {name}')


def classify_zone_op_error(errors: List[Dict[str, Any]]) -> Exception:
    """Map GCE operation error codes onto the failover taxonomy.

    ZONE_RESOURCE_POOL_EXHAUSTED (capacity) must fail over to the next
    zone; QUOTA_EXCEEDED must skip the region; anything else is classified
    by message so stockout phrasings are still caught.
    """
    codes = {e.get('code', '') for e in errors}
    msg = '; '.join(e.get('message', '') for e in errors)
    if codes & {'ZONE_RESOURCE_POOL_EXHAUSTED',
                'ZONE_RESOURCE_POOL_EXHAUSTED_WITH_DETAILS',
                'RESOURCE_POOL_EXHAUSTED'}:
        return exceptions.TpuStockoutError(f'GCE capacity error: {msg[:400]}')
    if codes & {'QUOTA_EXCEEDED'}:
        return exceptions.QuotaExceededError(f'GCE quota error: {msg[:400]}')
    return tpu_api.classify_http_error(409, msg)


def create_instance(project: str, zone: str, body: Dict[str, Any],
                    session=None) -> None:
    op = tpu_api._call('POST', instance_url(project, zone), body,  # pylint: disable=protected-access
                       session=session)
    _wait_zone_op(project, zone, op, session=session)


def get_instance(project: str, zone: str, name: str,
                 session=None) -> Optional[Dict[str, Any]]:
    try:
        return tpu_api._call('GET', instance_url(project, zone, name),  # pylint: disable=protected-access
                             session=session)
    except exceptions.ProvisionError as e:
        if '404' in str(e):
            return None
        raise


def delete_instance(project: str, zone: str, name: str, session=None) -> None:
    try:
        op = tpu_api._call('DELETE', instance_url(project, zone, name),  # pylint: disable=protected-access
                           session=session)
    except exceptions.ProvisionError as e:
        if '404' in str(e):
            return
        raise
    _wait_zone_op(project, zone, op, session=session)


def stop_instance(project: str, zone: str, name: str, session=None) -> None:
    op = tpu_api._call(  # pylint: disable=protected-access
        'POST', instance_url(project, zone, name) + '/stop', session=session)
    _wait_zone_op(project, zone, op, timeout=900, session=session)


def start_instance(project: str, zone: str, name: str, session=None) -> None:
    op = tpu_api._call(  # pylint: disable=protected-access
        'POST', instance_url(project, zone, name) + '/start', session=session)
    _wait_zone_op(project, zone, op, timeout=900, session=session)


def instance_ips(instance: Dict[str, Any]):
    nic = (instance.get('networkInterfaces') or [{}])[0]
    internal = nic.get('networkIP')
    access = (nic.get('accessConfigs') or [{}])[0]
    return internal, access.get('natIP')


def open_firewall_ports(project: str, ports: List[str],
                        session=None) -> None:
    """One allow-ingress rule per port range, tagged to skytpu VMs."""
    for port in ports:
        rule_name = f'skytpu-allow-{port.replace("-", "to")}'
        body = {
            'name': rule_name,
            'network': 'global/networks/default',
            'direction': 'INGRESS',
            'allowed': [{
                'IPProtocol': 'tcp',
                'ports': [port]
            }],
            'sourceRanges': ['0.0.0.0/0'],
            'targetTags': ['skytpu'],
        }
        url = f'{_COMPUTE_API}/projects/{project}/global/firewalls'
        try:
            tpu_api._call('POST', url, body, session=session)  # pylint: disable=protected-access
        except exceptions.ProvisionError as e:
            if '409' in str(e):  # already exists
                continue
            raise
