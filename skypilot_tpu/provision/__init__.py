"""Per-cloud provisioning: a stateless function interface routed by provider.

Parity: sky/provision/__init__.py:30-200 (_route_to_cloud_impl + the op
set).  Each provider module exposes:

    run_instances(region, zone, cluster_name, config) -> ProvisionRecord
    wait_instances(region, zone, cluster_name, state) -> None
    get_cluster_info(region, zone, cluster_name) -> ClusterInfo
    query_instances(cluster_name, provider_config) -> dict[id, status]
    stop_instances(cluster_name, provider_config) -> None
    terminate_instances(cluster_name, provider_config) -> None
    open_ports(cluster_name, ports, provider_config) -> None
    get_command_runners(cluster_info) -> list[CommandRunner]
"""
import importlib
from typing import Any, Callable


def _impl(provider: str):
    return importlib.import_module(f'skypilot_tpu.provision.{provider}')


def __getattr__(name: str) -> Callable[..., Any]:
    """provision.run_instances('gcp', ...) style dynamic routing."""
    ops = {
        'run_instances', 'wait_instances', 'get_cluster_info',
        'query_instances', 'stop_instances', 'terminate_instances',
        'open_ports', 'get_command_runners'
    }
    if name in ops:

        def route(provider: str, *args, **kwargs):
            return getattr(_impl(provider), name)(*args, **kwargs)

        route.__name__ = name
        return route
    raise AttributeError(name)
