"""Local provider: slices simulated as host directories + processes.

The serious job of this module is to make every backend/podlet code path
that a real TPU slice exercises — multi-host fan-out, head-host daemon,
partial failure, stockout failover — testable on one machine (the
reference's fake-cloud tier, SURVEY.md §4, but executing real jobs).

Cluster layout:  $SKYTPU_HOME/local_cloud/<cluster>/
    metadata.json          provider-level state (zone, status, num_hosts)
    host0/ ... hostN-1/    one dir per simulated host (HOME of that host)
"""
import json
import os
import shutil
import time
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.clouds import local as local_cloud
from skypilot_tpu.provision.common import (ClusterInfo, InstanceInfo,
                                           ProvisionRecord)
from skypilot_tpu.utils import command_runner, common, subprocess_utils


def _root() -> str:
    return os.path.join(common.home_dir(), 'local_cloud')


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(_root(), cluster_name)


def _metadata_path(cluster_name: str) -> str:
    return os.path.join(_cluster_dir(cluster_name), 'metadata.json')


def _load_metadata(cluster_name: str) -> Optional[dict]:
    try:
        with open(_metadata_path(cluster_name), 'r', encoding='utf-8') as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def _save_metadata(cluster_name: str, meta: dict) -> None:
    os.makedirs(_cluster_dir(cluster_name), exist_ok=True)
    with open(_metadata_path(cluster_name), 'w', encoding='utf-8') as f:
        json.dump(meta, f, indent=2)


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: Dict) -> ProvisionRecord:
    # Fault injection for failover tests: {zone: exception} or
    # {zone: int-count-of-failures-before-success}.
    fault = local_cloud.FAULT_INJECTION.get(zone)
    if fault is not None:
        if isinstance(fault, Exception):
            raise fault
        if isinstance(fault, int) and fault > 0:
            local_cloud.FAULT_INJECTION[zone] = fault - 1
            raise exceptions.TpuStockoutError(
                f'[local fault injection] no capacity in {zone}')
    existing = _load_metadata(cluster_name)
    num_hosts = int(config.get('num_hosts', 1))      # hosts PER slice
    num_slices = int(config.get('num_slices', 1))
    if existing is not None and existing.get('status') == 'running':
        return ProvisionRecord('local', cluster_name, region, zone,
                               resource_id=cluster_name, is_resume=True)
    meta = {
        'status': 'running',
        'region': region,
        'zone': zone,
        'num_hosts': num_hosts,
        'num_slices': num_slices,
        'chips_per_host': int(config.get('chips_per_host') or 0),
        'accelerator': config.get('accelerator'),
        'created_at': time.time(),
    }
    for i in range(num_hosts * num_slices):
        os.makedirs(os.path.join(_cluster_dir(cluster_name), f'host{i}'),
                    exist_ok=True)
    _save_metadata(cluster_name, meta)
    return ProvisionRecord('local', cluster_name, region, zone,
                           resource_id=cluster_name,
                           is_resume=existing is not None)


def wait_instances(region: str, zone: Optional[str], cluster_name: str,
                   state: str = 'running') -> None:
    del region, zone, state  # local provisioning is synchronous


def get_cluster_info(region: str, zone: Optional[str],
                     cluster_name: str) -> ClusterInfo:
    meta = _load_metadata(cluster_name)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    num_slices = int(meta.get('num_slices', 1))
    per_slice = meta['num_hosts']
    instances = []
    for i in range(per_slice * num_slices):
        host_dir = os.path.join(_cluster_dir(cluster_name), f'host{i}')
        instances.append(
            InstanceInfo(instance_id=f'{cluster_name}-host{i}',
                         internal_ip='127.0.0.1',
                         external_ip='127.0.0.1',
                         tags={'slice': str(i // per_slice)},
                         local_dir=host_dir))
    return ClusterInfo(cluster_name=cluster_name,
                       provider='local',
                       region=meta['region'],
                       zone=meta['zone'],
                       instances=instances,
                       accelerator=meta.get('accelerator'),
                       chips_per_host=meta.get('chips_per_host', 0),
                       num_slices=num_slices)


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    meta = _load_metadata(cluster_name)
    if meta is None:
        return {}
    status = meta.get('status', 'terminated')
    total = meta['num_hosts'] * int(meta.get('num_slices', 1))
    return {f'{cluster_name}-host{i}': status for i in range(total)}


def _kill_cluster_processes(cluster_name: str) -> None:
    """Kill podlet daemons / jobs whose HOME is inside this cluster dir.

    Never kills the calling process or its ancestors: on autodown this runs
    INSIDE the podlet daemon (whose HOME is host0), which must survive long
    enough to finish metadata cleanup — it exits on its own afterwards.
    """
    import psutil
    root = _cluster_dir(cluster_name)
    protected = set()
    try:
        p = psutil.Process()
        while p is not None:
            protected.add(p.pid)
            p = p.parent()
    except psutil.Error:
        protected.add(os.getpid())
    for proc in psutil.process_iter(['pid', 'environ']):
        try:
            if proc.info['pid'] in protected:
                continue
            env = proc.info['environ'] or {}
            if env.get('HOME', '').startswith(root):
                subprocess_utils.kill_process_tree(proc.info['pid'])
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            continue


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    meta = _load_metadata(cluster_name)
    if meta is None:
        return
    _kill_cluster_processes(cluster_name)
    meta['status'] = 'stopped'
    _save_metadata(cluster_name, meta)


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    if _load_metadata(cluster_name) is None:
        return
    _kill_cluster_processes(cluster_name)
    shutil.rmtree(_cluster_dir(cluster_name), ignore_errors=True)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Optional[Dict] = None) -> None:
    del cluster_name, ports  # localhost: nothing to open


def get_command_runners(
        cluster_info: ClusterInfo
) -> List[command_runner.CommandRunner]:
    return [
        command_runner.LocalProcessRunner(inst.local_dir, inst.instance_id)
        for inst in cluster_info.instances
    ]
