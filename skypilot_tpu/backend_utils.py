"""Cluster status refresh + reconciliation.

Parity: sky/backends/backend_utils.py — notably the status-refresh state
machine (_update_cluster_status_no_lock, :1669), check_cluster_available
(:2032) and get_clusters (:2302).  The reference's case analysis is ported
wholesale (SURVEY.md §7 hard part (f)), with `ray status` node counting
replaced by a podlet liveness probe.

State machine inputs per refresh:
  (a) provider-queried host statuses (running/pending/stopped/terminated);
  (b) podlet daemon liveness on the head host;
outputs: UP | INIT | STOPPED | <record removed>.
"""
import functools
import typing
from typing import Dict, List, Optional

from skypilot_tpu import exceptions, logsys, provision, state
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.utils import locks, subprocess_utils

if typing.TYPE_CHECKING:
    from skypilot_tpu.backends import SliceResourceHandle

logger = logsys.init_logger(__name__)


def _podlet_alive(handle: 'SliceResourceHandle') -> bool:
    """Is the podlet daemon healthy on the head host?  (The analog of the
    reference counting healthy nodes via `ray status`,
    backend_utils.py:944.)"""
    try:
        head = handle.get_command_runners(refresh=True)[0]
        rc = head.run(
            'kill -0 $(cat ~/.skytpu/podlet/pid 2>/dev/null) 2>/dev/null')
        return rc == 0
    except Exception:  # pylint: disable=broad-except
        return False


def _update_cluster_status_no_lock(cluster_name: str) -> Optional[Dict]:
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    handle = record['handle']
    try:
        statuses = provision.query_instances(handle.provider, cluster_name)
    except Exception as e:  # pylint: disable=broad-except
        logger.debug('query_instances failed for %s: %s', cluster_name, e)
        statuses = None
    if statuses is None:
        # Cloud query failed: keep the cached status (do not flap).
        return record
    if not statuses:
        # Nothing exists in the cloud: the slice was terminated out-of-band
        # (preemption, manual delete, autodown).  Drop the record.
        logger.debug('Cluster %r no longer exists in the cloud; removing.',
                     cluster_name)
        state.remove_cluster(cluster_name, terminate=True)
        return None
    values = list(statuses.values())
    expected_hosts = handle.num_hosts * handle.launched_nodes
    all_running = (values.count('running') == len(values) and
                   len(values) >= expected_hosts)
    any_running_or_pending = any(v in ('running', 'pending') for v in values)
    if all_running:
        if _podlet_alive(handle):
            state.update_cluster_status(cluster_name, ClusterStatus.UP)
        else:
            # Hosts up but runtime dead: abnormal -> INIT (a relaunch will
            # repair the runtime; parity with the reference demoting to
            # INIT on partial ray-node death).
            state.update_cluster_status(cluster_name, ClusterStatus.INIT)
    elif any_running_or_pending:
        # Partially alive slice (e.g. some hosts preempted): INIT signals
        # "abnormal, needs repair/teardown".
        state.update_cluster_status(cluster_name, ClusterStatus.INIT)
    else:
        # All hosts stopped. TPU slices cannot be stopped, so this only
        # happens for controller VMs.
        state.remove_cluster(cluster_name, terminate=False)
    return state.get_cluster_from_name(cluster_name)


def refresh_cluster_record(cluster_name: str,
                           acquire_lock: bool = True) -> Optional[Dict]:
    """Query the cloud and reconcile the local record.  Returns the fresh
    record, or None if the cluster no longer exists."""
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        return None
    if acquire_lock:
        import filelock
        try:
            with locks.cluster_status_lock(cluster_name, timeout=30):
                return _update_cluster_status_no_lock(cluster_name)
        except filelock.Timeout:
            # Another operation (e.g. a long provision) holds the lock;
            # return the cached record rather than blocking or crashing.
            logger.debug(
                'Cluster %r is locked by another operation; returning '
                'cached status.', cluster_name)
            return record
    return _update_cluster_status_no_lock(cluster_name)


def refresh_cluster_status_handle(cluster_name: str):
    record = refresh_cluster_record(cluster_name)
    if record is None:
        return None, None
    return record['status'], record['handle']


@functools.lru_cache(maxsize=None)
def _active_identity_cached(cloud_name: str):
    """Per-process memo of the active cloud identity: the GCP lookup
    shells out to gcloud (10 s timeout worst case), and every mutating
    op runs the owner check — one subprocess per process, not per op.
    An account switch mid-process is not observed, matching the
    reference's per-process identity caching."""
    from skypilot_tpu.clouds import Cloud
    return Cloud.from_name(cloud_name).get_active_user_identity()


def check_owner_identity(cluster_name: str) -> None:
    """Raise ClusterOwnerIdentityMismatchError when the ACTIVE cloud
    identity differs from the identity that created the cluster — a
    second gcloud account must not silently mutate another user's
    clusters.  Parity: reference check_owner_identity
    (sky/backends/backend_utils.py:1421).

    Identity-less clouds skip the check; records from before identities
    were recorded (or whose stored owner is the legacy user hash) are
    backfilled with the active identity instead of rejected."""
    import json

    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        return
    launched = getattr(record['handle'], 'launched_resources', None)
    if launched is None or launched.cloud is None:
        return
    active = _active_identity_cached(launched.cloud)
    if not active:
        return
    stored = record.get('owner')
    try:
        stored_list = json.loads(stored) if stored else None
    except (TypeError, ValueError):
        stored_list = None
    if not isinstance(stored_list, list) or not stored_list:
        state.set_cluster_owner(cluster_name, json.dumps(active))
        return
    # Element 0 is the primary identity (e.g. the gcloud account); the
    # rest is context (project id) and must not satisfy the check.
    if str(stored_list[0]) != str(active[0]):
        raise exceptions.ClusterOwnerIdentityMismatchError(
            f'Cluster {cluster_name!r} was created by cloud identity '
            f'{stored_list[0]!r}, but the active identity is '
            f'{active[0]!r}. Switch back (e.g. `gcloud config set '
            f'account {stored_list[0]}`) before mutating this cluster.')


def check_cluster_available(cluster_name: str):
    """Raise unless the cluster exists and is UP; returns its handle.
    Parity: backend_utils.check_cluster_available (:2032)."""
    record = state.get_cluster_from_name(cluster_name)
    if record is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    check_owner_identity(cluster_name)
    status, handle = refresh_cluster_status_handle(cluster_name)
    if status is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} no longer exists in the cloud.')
    if status != ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {status.value}, not UP.',
            cluster_status=status, handle=handle)
    return handle


def get_clusters(refresh: bool = False,
                 cluster_names: Optional[List[str]] = None) -> List[Dict]:
    records = state.get_clusters()
    if cluster_names is not None:
        records = [r for r in records if r['name'] in cluster_names]
    if not refresh:
        return records
    names = [r['name'] for r in records]

    def _refresh(name: str):
        return refresh_cluster_record(name)

    fresh = subprocess_utils.run_in_parallel(_refresh, names)
    return [r for r in fresh if r is not None]
