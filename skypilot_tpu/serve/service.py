"""Per-service entrypoint running on the serve controller host.

Parity: sky/serve/service.py:133 (_start) — launched as a job on the
controller cluster by `serve.up`; brings up the controller (autoscaler +
replica manager + HTTP API) and the load balancer, then waits on the
terminate signal file, tearing everything down on exit.  The reference
forks two processes; we run two daemon threads (both are stdlib HTTP
servers) and keep the main thread as the signal watcher.
"""
import argparse
import os
import threading
import time
import traceback

from skypilot_tpu import logsys
from skypilot_tpu.serve import constants, load_balancer, serve_state
from skypilot_tpu.serve.controller import ServeController
from skypilot_tpu.serve.load_balancing_policies import (DEFAULT_POLICY,
                                                        LoadBalancingPolicy)
from skypilot_tpu.serve.replica_managers import LoadBalancerSupervisor
from skypilot_tpu.serve.serve_state import ServiceStatus
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec
from skypilot_tpu.utils import locks

logger = logsys.init_logger(__name__)


def _allocate_ports() -> tuple:
    """Pick (controller_port, lb_port) unused by other services on this
    controller host (parity: the port-selection lock,
    sky/serve/service.py:187)."""
    used = set()
    for svc in serve_state.get_services():
        used.add(svc['controller_port'])
        used.add(svc['load_balancer_port'])
    cport = constants.CONTROLLER_PORT_START
    while cport in used:
        cport += 1
    lport = constants.LOAD_BALANCER_PORT_START
    while lport in used:
        lport += 1
    return cport, lport


def _signal_path(service_name: str) -> str:
    return os.path.join(os.path.expanduser(constants.SIGNAL_DIR),
                        service_name)


def _cleanup(service_name: str, controller: ServeController) -> None:
    serve_state.set_service_status(service_name, ServiceStatus.SHUTTING_DOWN)
    controller.replica_manager.terminate_all()
    serve_state.remove_service(service_name)
    try:
        os.remove(_signal_path(service_name))
    except FileNotFoundError:
        pass


def _start(service_name: str, task_yaml: str, policy_name: str) -> None:
    import yaml
    with open(os.path.expanduser(task_yaml), encoding='utf-8') as f:
        task_cfg = yaml.safe_load(f)
    if 'service' not in task_cfg:
        raise ValueError(f'No `service:` section in {task_yaml}')
    spec = SkyTpuServiceSpec.from_yaml_config(task_cfg['service'])
    LoadBalancingPolicy.make(policy_name)  # validate early

    with locks.named_lock('serve-ports'):
        controller_port, lb_port = _allocate_ports()
        ok = serve_state.add_service(service_name, controller_port, lb_port,
                                     policy_name, spec.to_json(), task_yaml,
                                     os.getpid())
    if not ok:
        raise RuntimeError(f'Service {service_name!r} already exists.')

    os.makedirs(os.path.expanduser(constants.SIGNAL_DIR), exist_ok=True)
    controller = ServeController(service_name, spec, task_yaml,
                                 controller_port)
    # The LB runs SUPERVISED, like a replica: probed on
    # lb_health_probe_interval, restarted on the same port after
    # lb_restart_threshold consecutive probe failures.  The factory
    # wires the warm-restart journal from SKYTPU_LB_JOURNAL, so each
    # restart re-adopts breaker/affinity/budget state instead of
    # relearning the fleet cold.
    supervisor = LoadBalancerSupervisor(
        lambda: load_balancer.make_load_balancer(
            f'http://127.0.0.1:{controller_port}', lb_port, policy_name))
    controller.lb_supervisor = supervisor

    threading.Thread(target=controller.run, daemon=True,
                     name='controller').start()
    supervisor.start()
    serve_state.set_service_status(service_name, ServiceStatus.REPLICA_INIT)
    logger.info('Service %r up: controller :%d, load balancer :%d',
                service_name, controller_port, lb_port)

    signal = _signal_path(service_name)
    try:
        while True:
            if os.path.exists(signal):
                logger.info('Terminate signal received for %r.',
                            service_name)
                break
            time.sleep(1)
    finally:
        supervisor.stop()
        controller.stop()
        _cleanup(service_name, controller)
    logger.info('Service %r torn down.', service_name)


def main() -> None:
    parser = argparse.ArgumentParser('skypilot_tpu.serve.service')
    parser.add_argument('--service-name', required=True)
    parser.add_argument('--task-yaml', required=True)
    parser.add_argument('--policy', default=DEFAULT_POLICY)
    args = parser.parse_args()
    try:
        _start(args.service_name, args.task_yaml, args.policy)
    except Exception:
        logger.error('Service %r crashed:\n%s', args.service_name,
                     traceback.format_exc())
        svc = serve_state.get_service(args.service_name)
        # Only mark failed if the crashing process owns the record (a
        # duplicate-name `up` must not poison the live service).
        if svc is not None and svc['controller_pid'] == os.getpid():
            serve_state.set_service_status(args.service_name,
                                           ServiceStatus.CONTROLLER_FAILED)
        raise


if __name__ == '__main__':
    main()
