"""Per-replica circuit breaker for the load balancer.

closed -> open -> half-open, the classic shape:

- **closed**: traffic flows; consecutive connection-level failures are
  counted.  At ``failure_threshold`` the breaker OPENS.
- **open**: the replica is ejected from routing for a backoff window
  (exponential in the number of consecutive opens, jittered so a fleet
  of LBs doesn't re-probe a recovering replica in lockstep).
- **half-open**: once the window elapses, ``available()`` turns true
  again — the next probe/request is the trial.  Success closes the
  breaker (backoff resets); failure re-opens it with a doubled window.

Only CONNECTION-level failures (refused, reset, timeout) count: any
HTTP response — including a 404 from a replica that doesn't implement
/healthz — proves a live process, so application-level status never
opens the breaker.  That keeps the LB safe in front of plain HTTP
replicas (the e2e tests serve `python3 -m http.server`).

A fourth state rides alongside the classic three: **probation**, the
gray-failure track.  Connection failures prove a replica *dead*;
probation catches one that is *alive but lying* — answering probes
while its TTFT drifts to many multiples of the fleet median (fail-slow).
The LB feeds per-replica TTFT samples into an EWMA and periodically
calls ``evaluate_probation(fleet_median)``; a replica sustained above
``probation_k`` x median for ``probation_enter`` consecutive
evaluations enters probation (the LB sheds its routing weight to
~10%), and needs ``probation_exit`` consecutive clean evaluations to
leave — hysteresis on both edges so one GC pause doesn't eject and one
lucky request doesn't readmit.  Probation never blocks traffic
outright (the replica keeps a trickle + probes): it is a weight, not a
wall, so a fleet-wide slowdown cannot eject everyone.

Deterministic by construction: the clock and the jitter RNG are
injected, so tests drive every transition without a single sleep.
"""
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from skypilot_tpu.analysis import sanitizers


class CircuitBreaker:

    CLOSED = 'closed'
    OPEN = 'open'
    HALF_OPEN = 'half_open'
    PROBATION = 'probation'

    def __init__(self,
                 failure_threshold: int = 2,
                 base_backoff_s: float = 1.0,
                 max_backoff_s: float = 30.0,
                 jitter_frac: float = 0.2,
                 now: Callable[[], float] = time.monotonic,
                 rng: Optional[np.random.Generator] = None,
                 probation_k: float = 3.0,
                 probation_enter: int = 3,
                 probation_exit: int = 3,
                 ewma_alpha: float = 0.3):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1')
        self.failure_threshold = failure_threshold
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter_frac = jitter_frac
        self.probation_k = probation_k
        self.probation_enter = max(1, int(probation_enter))
        self.probation_exit = max(1, int(probation_exit))
        self.ewma_alpha = ewma_alpha
        self._now = now
        self._rng = rng if rng is not None else np.random.default_rng(0)
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.circuit_breaker._lock')
        self._failures = 0  # guarded-by: _lock (consecutive, while closed)
        self._opens = 0  # guarded-by: _lock (consecutive opens = backoff exp)
        self._open_until: Optional[float] = None  # guarded-by: _lock
        self.open_count = 0  # guarded-by: _lock (lifetime opens)
        self._lat_ewma: Optional[float] = None  # guarded-by: _lock
        self._outlier_streak = 0  # guarded-by: _lock (consecutive outlier evals)
        self._clear_streak = 0  # guarded-by: _lock (consecutive clean evals)
        self._probation = False  # guarded-by: _lock
        # Fired OUTSIDE the lock with the new state name on every
        # open/close/probation edge; the LB hangs its journal fsync here.
        self.on_transition: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            if self._open_until is not None:
                if self._now() >= self._open_until:
                    return self.HALF_OPEN
                return self.OPEN
            if self._probation:
                return self.PROBATION
            return self.CLOSED

    def available(self) -> bool:
        """True when the replica may receive traffic: closed, or open
        with the backoff elapsed (half-open trial)."""
        with self._lock:
            return (self._open_until is None or
                    self._now() >= self._open_until)

    # ----------------------------------------------------------- outcomes

    def record_success(self) -> None:
        """Any HTTP response (probe or proxied request reached the
        replica): close the breaker, reset failures and backoff."""
        fire = None
        with self._lock:
            was_open = self._open_until is not None
            self._failures = 0
            self._opens = 0
            self._open_until = None
            if was_open:
                fire = (self.PROBATION if self._probation else self.CLOSED)
        self._fire(fire)

    def record_failure(self) -> None:
        """A connection-level failure (refused/reset/timeout).  While
        closed, counts toward the threshold; in half-open, re-opens
        immediately with a doubled window."""
        fire = None
        with self._lock:
            if self._open_until is not None:
                if self._now() >= self._open_until:
                    # Half-open trial failed: re-open, doubled window.
                    self._trip()
                    fire = self.OPEN
                # Still open: probes/stragglers hitting a known-dead
                # replica add no information — re-arming here would
                # double the backoff per PROBE instead of per trial
                # and inflate open_count.
            else:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._trip()
                    fire = self.OPEN
        self._fire(fire)

    def _trip(self) -> None:  # locked: _lock
        """(Caller holds the lock.)  Open with exponential backoff +
        jitter: window = base * 2^opens * (1 +- jitter_frac)."""
        backoff = min(self.max_backoff_s,
                      self.base_backoff_s * (2.0 ** self._opens))
        jitter = 1.0 + self.jitter_frac * (
            2.0 * float(self._rng.random()) - 1.0)
        self._open_until = self._now() + backoff * jitter
        self._opens += 1
        self._failures = 0
        self.open_count += 1

    def _fire(self, state: Optional[str]) -> None:
        """Invoke on_transition outside the lock (the callback may
        fsync a journal or take other locks; holding _lock across it
        would invert lock order with the LB's stats lock)."""
        if state is not None and self.on_transition is not None:
            self.on_transition(state)

    # ------------------------------------------- gray-failure (probation)

    def record_latency(self, seconds: float) -> None:
        """Feed one TTFT sample into the latency EWMA.  Cheap enough to
        call per request; the EWMA (not the raw sample) is what
        evaluate_probation() compares against the fleet median."""
        with self._lock:
            if self._lat_ewma is None:
                self._lat_ewma = float(seconds)
            else:
                a = self.ewma_alpha
                self._lat_ewma = a * float(seconds) + (1.0 - a) * self._lat_ewma

    @property
    def latency_ewma(self) -> Optional[float]:
        with self._lock:
            return self._lat_ewma

    def in_probation(self) -> bool:
        with self._lock:
            return self._probation

    def evaluate_probation(self, fleet_median: float) -> bool:
        """One probation evaluation against the fleet TTFT median.
        Returns True iff the probation flag CHANGED this call.  A
        replica with no EWMA yet (no traffic) counts as clean — absence
        of samples is not evidence of slowness."""
        fire = None
        changed = False
        with self._lock:
            outlier = (self._lat_ewma is not None and fleet_median > 0.0
                       and self._lat_ewma > self.probation_k * fleet_median)
            if outlier:
                self._outlier_streak += 1
                self._clear_streak = 0
                if (not self._probation
                        and self._outlier_streak >= self.probation_enter):
                    self._probation = True
                    changed = True
                    fire = self.PROBATION
            else:
                self._clear_streak += 1
                self._outlier_streak = 0
                if (self._probation
                        and self._clear_streak >= self.probation_exit):
                    self._probation = False
                    # Exiting probation sheds the stale EWMA: the next
                    # verdict should rest on post-recovery samples, not
                    # on the slow era's memory.
                    self._lat_ewma = None
                    changed = True
                    fire = self.CLOSED
        self._fire(fire)
        return changed

    def reset_latency_state(self) -> bool:
        """Forget all gray-failure evidence: latency EWMA, hysteresis
        streaks, and the probation flag.  Probation normally exits by
        accumulating fresh healthy samples, but a replica that stopped
        receiving traffic keeps its stale EWMA forever — an operator
        (or a test harness isolating fault episodes) may know the
        evidence no longer describes the replica.  Returns True iff the
        replica actually left probation (the edge is journalled via
        on_transition, like a natural exit)."""
        with self._lock:
            was = self._probation
            self._probation = False
            self._lat_ewma = None
            self._outlier_streak = 0
            self._clear_streak = 0
        if was:
            self._fire(self.CLOSED)
        return was

    # ------------------------------------------------- journal snapshot

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serialisable state for the LB journal.  The backoff
        deadline is stored RELATIVE (seconds remaining) because the
        injected clock is monotonic — absolute readings don't survive a
        process restart."""
        with self._lock:
            remaining = None
            if self._open_until is not None:
                remaining = max(0.0, self._open_until - self._now())
            return {
                'failures': self._failures,
                'opens': self._opens,
                'open_remaining_s': remaining,
                'open_count': self.open_count,
                'probation': self._probation,
                'outlier_streak': self._outlier_streak,
                'clear_streak': self._clear_streak,
                'latency_ewma': self._lat_ewma,
            }

    def restore(self, snap: Dict[str, Any]) -> None:
        """Re-adopt a snapshot() doc after a restart.  Tolerant of
        missing keys (journal written by an older LB)."""
        with self._lock:
            self._failures = int(snap.get('failures', 0))
            self._opens = int(snap.get('opens', 0))
            remaining = snap.get('open_remaining_s')
            self._open_until = (None if remaining is None
                                else self._now() + float(remaining))
            self.open_count = int(snap.get('open_count', 0))
            self._probation = bool(snap.get('probation', False))
            self._outlier_streak = int(snap.get('outlier_streak', 0))
            self._clear_streak = int(snap.get('clear_streak', 0))
            ewma = snap.get('latency_ewma')
            self._lat_ewma = None if ewma is None else float(ewma)
