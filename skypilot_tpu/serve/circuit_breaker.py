"""Per-replica circuit breaker for the load balancer.

closed -> open -> half-open, the classic shape:

- **closed**: traffic flows; consecutive connection-level failures are
  counted.  At ``failure_threshold`` the breaker OPENS.
- **open**: the replica is ejected from routing for a backoff window
  (exponential in the number of consecutive opens, jittered so a fleet
  of LBs doesn't re-probe a recovering replica in lockstep).
- **half-open**: once the window elapses, ``available()`` turns true
  again — the next probe/request is the trial.  Success closes the
  breaker (backoff resets); failure re-opens it with a doubled window.

Only CONNECTION-level failures (refused, reset, timeout) count: any
HTTP response — including a 404 from a replica that doesn't implement
/healthz — proves a live process, so application-level status never
opens the breaker.  That keeps the LB safe in front of plain HTTP
replicas (the e2e tests serve `python3 -m http.server`).

Deterministic by construction: the clock and the jitter RNG are
injected, so tests drive every transition without a single sleep.
"""
import threading
import time
from typing import Callable, Optional

import numpy as np

from skypilot_tpu.analysis import sanitizers


class CircuitBreaker:

    CLOSED = 'closed'
    OPEN = 'open'
    HALF_OPEN = 'half_open'

    def __init__(self,
                 failure_threshold: int = 2,
                 base_backoff_s: float = 1.0,
                 max_backoff_s: float = 30.0,
                 jitter_frac: float = 0.2,
                 now: Callable[[], float] = time.monotonic,
                 rng: Optional[np.random.Generator] = None):
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1')
        self.failure_threshold = failure_threshold
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter_frac = jitter_frac
        self._now = now
        self._rng = rng if rng is not None else np.random.default_rng(0)
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.circuit_breaker._lock')
        self._failures = 0  # guarded-by: _lock (consecutive, while closed)
        self._opens = 0  # guarded-by: _lock (consecutive opens = backoff exp)
        self._open_until: Optional[float] = None  # guarded-by: _lock
        self.open_count = 0  # guarded-by: _lock (lifetime opens)

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            if self._open_until is None:
                return self.CLOSED
            if self._now() >= self._open_until:
                return self.HALF_OPEN
            return self.OPEN

    def available(self) -> bool:
        """True when the replica may receive traffic: closed, or open
        with the backoff elapsed (half-open trial)."""
        with self._lock:
            return (self._open_until is None or
                    self._now() >= self._open_until)

    # ----------------------------------------------------------- outcomes

    def record_success(self) -> None:
        """Any HTTP response (probe or proxied request reached the
        replica): close the breaker, reset failures and backoff."""
        with self._lock:
            self._failures = 0
            self._opens = 0
            self._open_until = None

    def record_failure(self) -> None:
        """A connection-level failure (refused/reset/timeout).  While
        closed, counts toward the threshold; in half-open, re-opens
        immediately with a doubled window."""
        with self._lock:
            if self._open_until is not None:
                if self._now() >= self._open_until:
                    # Half-open trial failed: re-open, doubled window.
                    self._trip()
                # Still open: probes/stragglers hitting a known-dead
                # replica add no information — re-arming here would
                # double the backoff per PROBE instead of per trial
                # and inflate open_count.
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()

    def _trip(self) -> None:  # locked: _lock
        """(Caller holds the lock.)  Open with exponential backoff +
        jitter: window = base * 2^opens * (1 +- jitter_frac)."""
        backoff = min(self.max_backoff_s,
                      self.base_backoff_s * (2.0 ** self._opens))
        jitter = 1.0 + self.jitter_frac * (
            2.0 * float(self._rng.random()) - 1.0)
        self._open_until = self._now() + backoff * jitter
        self._opens += 1
        self._failures = 0
        self.open_count += 1
