"""The `service:` section of a task YAML.

Parity: sky/serve/service_spec.py:15 (SkyServiceSpec) — readiness probe
(path/post_data/headers/initial delay/timeout), replica policy (min/max
replicas, target QPS per replica, hysteresis delays, spot + on-demand
fallback), and the replica port.
"""
import dataclasses
import json
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.serve import constants


@dataclasses.dataclass
class SkyTpuServiceSpec:
    """Validated service specification."""
    # Readiness probe.
    readiness_path: str = '/'
    initial_delay_seconds: float = 1200.0
    readiness_timeout_seconds: float = 15.0
    post_data: Optional[Any] = None
    readiness_headers: Optional[Dict[str, str]] = None
    # Replica policy.
    min_replicas: int = 1
    max_replicas: Optional[int] = None      # None => fixed at min_replicas
    target_qps_per_replica: Optional[float] = None
    upscale_delay_seconds: float = 300.0
    downscale_delay_seconds: float = 1200.0
    # SLO-driven autoscaling (alternative to target_qps_per_replica):
    # scale so the fleet's worst per-replica TTFT p95 stays under
    # slo_ttft_ms.  slo_tpot_ms is recorded for observability/benching
    # (decode-rate SLO); the autoscaler currently tracks TTFT.
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # LB-edge QoS: None/'off' (no per-tenant rate limiting knobs pushed)
    # or 'tenant_rate' (per-tenant token buckets; rates come from the
    # SKYTPU_SERVE_QOS_* environment knobs on the LB host).
    qos_policy: Optional[str] = None
    # Spot policy (FallbackRequestRateAutoscaler parity).
    use_ondemand_fallback: bool = False
    base_ondemand_fallback_replicas: int = 0
    # Traffic.
    port: int = constants.DEFAULT_REPLICA_PORT
    load_balancing_policy: Optional[str] = None

    def __post_init__(self):
        if self.min_replicas < 0:
            raise exceptions.InvalidTaskError('min_replicas must be >= 0')
        if (self.max_replicas is not None and
                self.max_replicas < self.min_replicas):
            raise exceptions.InvalidTaskError(
                'max_replicas must be >= min_replicas')
        if self.target_qps_per_replica is not None:
            if self.target_qps_per_replica <= 0:
                raise exceptions.InvalidTaskError(
                    'target_qps_per_replica must be > 0')
            if self.max_replicas is None:
                raise exceptions.InvalidTaskError(
                    'target_qps_per_replica requires max_replicas')
        if self.slo_ttft_ms is not None:
            if self.slo_ttft_ms <= 0:
                raise exceptions.InvalidTaskError('slo_ttft_ms must be > 0')
            if self.max_replicas is None:
                raise exceptions.InvalidTaskError(
                    'slo_ttft_ms requires max_replicas')
            if self.target_qps_per_replica is not None:
                raise exceptions.InvalidTaskError(
                    'slo_ttft_ms and target_qps_per_replica are mutually '
                    'exclusive: pick ONE autoscaling signal')
        if self.slo_tpot_ms is not None and self.slo_tpot_ms <= 0:
            raise exceptions.InvalidTaskError('slo_tpot_ms must be > 0')
        if self.qos_policy not in (None, 'off', 'tenant_rate'):
            raise exceptions.InvalidTaskError(
                f'qos_policy must be "off" or "tenant_rate", got '
                f'{self.qos_policy!r}')
        if not self.readiness_path.startswith('/'):
            raise exceptions.InvalidTaskError(
                f'readiness path must start with "/": '
                f'{self.readiness_path!r}')

    @property
    def autoscaling_enabled(self) -> bool:
        return (self.target_qps_per_replica is not None or
                self.slo_ttft_ms is not None)

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyTpuServiceSpec':
        """Accepts both the nested reference schema
        (`readiness_probe: {...}, replica_policy: {...}`) and flat keys."""
        if not isinstance(config, dict):
            raise exceptions.InvalidTaskError(
                f'service section must be a mapping, got {config!r}')
        kwargs: Dict[str, Any] = {}
        probe = config.get('readiness_probe', {})
        if isinstance(probe, str):
            probe = {'path': probe}
        if 'path' in probe:
            kwargs['readiness_path'] = probe['path']
        if 'initial_delay_seconds' in probe:
            kwargs['initial_delay_seconds'] = float(
                probe['initial_delay_seconds'])
        if 'timeout_seconds' in probe:
            kwargs['readiness_timeout_seconds'] = float(
                probe['timeout_seconds'])
        if 'post_data' in probe:
            kwargs['post_data'] = probe['post_data']
        if 'headers' in probe:
            kwargs['readiness_headers'] = dict(probe['headers'])

        policy = config.get('replica_policy', {})
        if 'replicas' in config:            # static shorthand
            kwargs['min_replicas'] = int(config['replicas'])
        if 'min_replicas' in policy:
            kwargs['min_replicas'] = int(policy['min_replicas'])
        if 'max_replicas' in policy:
            kwargs['max_replicas'] = int(policy['max_replicas'])
        if 'target_qps_per_replica' in policy:
            kwargs['target_qps_per_replica'] = float(
                policy['target_qps_per_replica'])
        if 'upscale_delay_seconds' in policy:
            kwargs['upscale_delay_seconds'] = float(
                policy['upscale_delay_seconds'])
        if 'downscale_delay_seconds' in policy:
            kwargs['downscale_delay_seconds'] = float(
                policy['downscale_delay_seconds'])
        if 'base_ondemand_fallback_replicas' in policy:
            kwargs['base_ondemand_fallback_replicas'] = int(
                policy['base_ondemand_fallback_replicas'])
        if 'dynamic_ondemand_fallback' in policy:
            kwargs['use_ondemand_fallback'] = bool(
                policy['dynamic_ondemand_fallback'])
        if 'slo_ttft_ms' in policy:
            kwargs['slo_ttft_ms'] = float(policy['slo_ttft_ms'])
        if 'slo_tpot_ms' in policy:
            kwargs['slo_tpot_ms'] = float(policy['slo_tpot_ms'])
        if 'port' in config:
            kwargs['port'] = int(config['port'])
        if 'load_balancing_policy' in config:
            kwargs['load_balancing_policy'] = config[
                'load_balancing_policy']
        if 'qos_policy' in config:
            kwargs['qos_policy'] = config['qos_policy']
        return cls(**kwargs)

    def to_yaml_config(self) -> Dict[str, Any]:
        probe: Dict[str, Any] = {
            'path': self.readiness_path,
            'initial_delay_seconds': self.initial_delay_seconds,
            'timeout_seconds': self.readiness_timeout_seconds,
        }
        if self.post_data is not None:
            probe['post_data'] = self.post_data
        if self.readiness_headers is not None:
            probe['headers'] = self.readiness_headers
        policy: Dict[str, Any] = {'min_replicas': self.min_replicas}
        if self.max_replicas is not None:
            policy['max_replicas'] = self.max_replicas
        if self.target_qps_per_replica is not None:
            policy['target_qps_per_replica'] = self.target_qps_per_replica
            policy['upscale_delay_seconds'] = self.upscale_delay_seconds
            policy['downscale_delay_seconds'] = self.downscale_delay_seconds
        if self.base_ondemand_fallback_replicas > 0:
            policy['base_ondemand_fallback_replicas'] = (
                self.base_ondemand_fallback_replicas)
        if self.use_ondemand_fallback:
            policy['dynamic_ondemand_fallback'] = True
        if self.slo_ttft_ms is not None:
            policy['slo_ttft_ms'] = self.slo_ttft_ms
            policy['upscale_delay_seconds'] = self.upscale_delay_seconds
            policy['downscale_delay_seconds'] = self.downscale_delay_seconds
        if self.slo_tpot_ms is not None:
            policy['slo_tpot_ms'] = self.slo_tpot_ms
        cfg: Dict[str, Any] = {
            'readiness_probe': probe,
            'replica_policy': policy,
            'port': self.port,
        }
        if self.load_balancing_policy is not None:
            cfg['load_balancing_policy'] = self.load_balancing_policy
        if self.qos_policy is not None:
            cfg['qos_policy'] = self.qos_policy
        return cfg

    def to_json(self) -> str:
        return json.dumps(self.to_yaml_config())

    @classmethod
    def from_json(cls, s: str) -> 'SkyTpuServiceSpec':
        return cls.from_yaml_config(json.loads(s))

    def __repr__(self) -> str:
        scale = (f'{self.min_replicas}..{self.max_replicas}'
                 if self.autoscaling_enabled else str(self.min_replicas))
        return (f'ServiceSpec(replicas={scale}, port={self.port}, '
                f'probe={self.readiness_path!r})')
