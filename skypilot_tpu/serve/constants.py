"""Serve plane constants (parity: sky/serve/constants.py).

Every interval has an env knob so e2e tests on the local cloud can run the
whole control loop in seconds instead of minutes.
"""
import os


def _f(env: str, default: float) -> float:
    return float(os.environ.get(env, default))


# Controller-host directory layout (HOME-relative: same code runs on real
# controller VMs and simulated local hosts).
SERVE_DIR = '~/.skytpu/serve'
SIGNAL_DIR = '~/.skytpu/serve/signals'

# Port ranges on the controller host.  Each service gets one controller
# port (autoscaler/replica-manager HTTP API) and one load-balancer port
# (user traffic).  Parity: sky/serve/constants.py CONTROLLER_PORT_START /
# LOAD_BALANCER_PORT_START.
CONTROLLER_PORT_START = 20001
LOAD_BALANCER_PORT_START = 30001

# Default replica port when the service spec does not give one.
DEFAULT_REPLICA_PORT = 8080

# Loop intervals (seconds).
def autoscaler_interval() -> float:
    return _f('SKYTPU_SERVE_AUTOSCALER_INTERVAL', 20.0)


def probe_interval() -> float:
    return _f('SKYTPU_SERVE_PROBE_INTERVAL', 10.0)


def lb_sync_interval() -> float:
    return _f('SKYTPU_SERVE_LB_SYNC_INTERVAL', 20.0)


def lb_health_probe_interval() -> float:
    """Load balancer's ACTIVE /healthz probe interval.  Much shorter
    than the controller sync: a dead replica is ejected from routing in
    probe-time (seconds) instead of controller-sync-time."""
    return _f('SKYTPU_SERVE_LB_PROBE_INTERVAL', 2.0)


def drain_timeout() -> float:
    """How long a draining replica gets to finish in-flight requests
    before teardown proceeds anyway."""
    return _f('SKYTPU_SERVE_DRAIN_TIMEOUT', 60.0)


def job_status_interval() -> float:
    return _f('SKYTPU_SERVE_JOB_STATUS_INTERVAL', 30.0)


def readiness_timeout() -> float:
    return _f('SKYTPU_SERVE_READINESS_TIMEOUT', 15.0)


# Consecutive probe failures after a replica has been READY before we mark
# it NOT_READY and replace it.
PROBE_FAILURE_THRESHOLD = 3

# How long `serve up` waits for the service record to appear / endpoint to
# come up before returning.
def up_wait_timeout() -> float:
    return _f('SKYTPU_SERVE_UP_TIMEOUT', 300.0)


# QPS window the autoscaler evaluates over.
def qps_window_seconds() -> float:
    return _f('SKYTPU_SERVE_QPS_WINDOW', 60.0)


# ------------------------- prefix-affinity routing (--lb-policy
# prefix_affinity) knobs.  Read once at policy construction.

def affinity_vnodes() -> int:
    """Virtual nodes per replica on the consistent-hash ring.  More
    vnodes = smoother key distribution, slower ring rebuilds."""
    return int(_f('SKYTPU_SERVE_AFFINITY_VNODES', 64))


def affinity_route_blocks() -> int:
    """How many leading kv_block_size-token runs feed the route key.
    Prompts sharing at least this many leading blocks hash to the same
    replica; the default (4 blocks = 64 tokens at block size 16) covers
    typical shared system prompts without splitting them."""
    return int(_f('SKYTPU_SERVE_AFFINITY_ROUTE_BLOCKS', 4))


def affinity_track_blocks() -> int:
    """Per-prefix residency tracking depth (blocks).  Deeper tracking
    lets failover pick the survivor with the longest cached prefix at
    finer granularity; memory is one map entry per depth."""
    return int(_f('SKYTPU_SERVE_AFFINITY_TRACK_BLOCKS', 16))


def affinity_block_size() -> int:
    """Fallback token-run length for the route key until a replica
    /healthz reports its real kv_block_size."""
    return int(_f('SKYTPU_SERVE_AFFINITY_BLOCK_SIZE', 16))


def affinity_load_factor() -> float:
    """Bounded-load consistent hashing factor: the ring owner is taken
    only while its outstanding count stays under
    factor * mean_outstanding + slack (Mirrokni et al.'s consistent
    hashing with bounded loads, plus an absolute slack so tiny fleets
    don't thrash)."""
    return _f('SKYTPU_SERVE_AFFINITY_LOAD_FACTOR', 1.25)


def affinity_load_slack() -> float:
    return _f('SKYTPU_SERVE_AFFINITY_LOAD_SLACK', 2.0)


def affinity_hit_rate_weight() -> float:
    """How much the fleet's observed radix hit rate raises the load
    bound: affinity is worth more imbalance when it is actually paying
    off (effective factor = load_factor + weight * fleet_hit_rate)."""
    return _f('SKYTPU_SERVE_AFFINITY_HIT_WEIGHT', 0.5)


def affinity_occupancy_high() -> float:
    """KV pool occupancy at which a replica is considered cache-full:
    routing new prefixes there would thrash its radix tree, so its
    effective load gets affinity_occupancy_penalty added."""
    return _f('SKYTPU_SERVE_AFFINITY_OCC_HIGH', 0.9)


def affinity_occupancy_penalty() -> float:
    return _f('SKYTPU_SERVE_AFFINITY_OCC_PENALTY', 2.0)


# --------------------------------------------------------------- QoS
# LB-level per-tenant token-bucket rate limits + SLO autoscaling
# (serve/qos.py, autoscalers.SloLatencyAutoscaler).  Rates are
# requests/second; <= 0 disables limiting for that scope.


def qos_default_rate() -> float:
    """Per-tenant request rate every tenant gets unless overridden by
    SKYTPU_SERVE_QOS_TENANT_RATES.  <= 0 (the default) = unlimited:
    turning the qos_policy on without configuring rates must not
    reject anyone."""
    return _f('SKYTPU_SERVE_QOS_RATE', 0.0)


def qos_default_burst() -> float:
    """Bucket capacity (requests) for tenants using the default rate;
    <= 0 falls back to max(1, rate) — one second of traffic."""
    return _f('SKYTPU_SERVE_QOS_BURST', 0.0)


def qos_tenant_rates() -> dict:
    """Per-tenant overrides: SKYTPU_SERVE_QOS_TENANT_RATES=
    'teamA=5,teamB=0.5' (requests/second).  Malformed entries are
    ignored rather than taking the LB down."""
    out = {}
    for part in os.environ.get('SKYTPU_SERVE_QOS_TENANT_RATES',
                               '').split(','):
        part = part.strip()
        if not part or '=' not in part:
            continue
        tenant, rate = part.split('=', 1)
        try:
            out[tenant.strip()] = float(rate)
        except ValueError:
            continue
    return out


def slo_latency_window() -> int:
    """Rolling per-replica latency samples the LB keeps for the SLO
    autoscaler signal (and /lb/stats)."""
    return int(_f('SKYTPU_SERVE_SLO_WINDOW', 256))


def slo_downscale_factor() -> float:
    """SLO autoscaler scales DOWN only while observed TTFT stays under
    this fraction of the target (hysteresis band: between factor*SLO
    and SLO the fleet holds)."""
    return _f('SKYTPU_SERVE_SLO_DOWNSCALE_FACTOR', 0.5)


# ------------------------------------------- control-plane resilience
# (PR 18): LB warm-restart journal, gray-failure probation, retry
# budgets, TTFT hedging.  The SKYTPU_LB_* prefix is the env contract
# documented in docs/serving.md "Control-plane fault tolerance".


def lb_hedge_ms() -> float:
    """TTFT hedge deadline in milliseconds for resumable greedy
    streams: if the first byte hasn't arrived by this deadline the LB
    issues the request to the affinity ring's next-best replica and
    keeps whichever answers first.  <= 0 (the default) disables
    hedging — it spends extra replica work for tail latency and must
    be an explicit choice."""
    return _f('SKYTPU_LB_HEDGE_MS', 0.0)


def lb_retry_budget_ratio() -> float:
    """Retry-budget deposit per successful request (Finagle-style
    refill proportional to successes): the fleet can spend at most
    ~ratio extra attempts per success under sustained failure."""
    return _f('SKYTPU_LB_RETRY_RATIO', 0.2)


def lb_retry_budget_reserve() -> float:
    """Constant retry-token trickle (tokens/second) so a cold or
    zero-throughput fleet can still retry occasionally."""
    return _f('SKYTPU_LB_RETRY_RESERVE', 0.1)


def lb_retry_budget_cap() -> float:
    """Retry-budget bucket capacity (tokens); the budget starts full."""
    return _f('SKYTPU_LB_RETRY_CAP', 100.0)


def lb_probation_k() -> float:
    """Gray-failure threshold: a replica whose TTFT EWMA sustains above
    k x the fleet median enters probation."""
    return _f('SKYTPU_LB_PROBATION_K', 3.0)


def lb_probation_enter() -> int:
    """Consecutive outlier evaluations (one per probe round) required
    to ENTER probation — hysteresis so one GC pause doesn't eject."""
    return int(_f('SKYTPU_LB_PROBATION_ENTER', 3))


def lb_probation_exit() -> int:
    """Consecutive clean evaluations required to LEAVE probation."""
    return int(_f('SKYTPU_LB_PROBATION_EXIT', 3))


def lb_probation_weight() -> float:
    """Fraction of its normal traffic a probation replica keeps (it is
    shed, not ejected: still probed, still convalescing on a trickle)."""
    return _f('SKYTPU_LB_PROBATION_WEIGHT', 0.1)


def lb_ewma_alpha() -> float:
    """EWMA smoothing factor for the per-replica TTFT track feeding
    probation evaluation."""
    return _f('SKYTPU_LB_EWMA_ALPHA', 0.3)


def lb_journal_path() -> str:
    """Warm-restart journal path; empty (the default) disables
    journalling entirely — the LB then restarts cold, exactly the
    pre-PR-18 behaviour."""
    return os.environ.get('SKYTPU_LB_JOURNAL', '')


def lb_journal_compact_every() -> int:
    """Appends between journal compactions (rewrite to one line per
    live key)."""
    return int(_f('SKYTPU_LB_JOURNAL_COMPACT_EVERY', 256))


def lb_restart_threshold() -> int:
    """Consecutive failed LB health probes before the supervisor
    restarts the LB process/thread on the same port."""
    return int(_f('SKYTPU_LB_RESTART_THRESHOLD', 3))


def batch_journal_path() -> str:
    """Batch-job journal path; empty (the default) means the batch
    plane is disabled on the controller — `POST /v1/batches` answers a
    typed 503 until the operator points this somewhere durable."""
    return os.environ.get('SKYTPU_BATCH_JOURNAL', '')


def batch_spool_dir() -> str:
    """Directory completed batch rows spool to, keyed by
    (job_id, row_idx); defaults to a `spool/` sibling of the journal."""
    return os.environ.get('SKYTPU_BATCH_SPOOL', '')


def batch_row_workers() -> int:
    """Concurrent batch rows in flight through the LB per job (the
    fleet's QoS plane, not this fan-out, decides actual admission)."""
    return int(_f('SKYTPU_BATCH_ROW_WORKERS', 4))


def batch_checkpoint_every() -> int:
    """Completed rows between fsync'd job checkpoints — the replay
    window a controller crash can force the coordinator to re-verify
    (never re-run: completed rows dedup by content hash)."""
    return int(_f('SKYTPU_BATCH_CHECKPOINT_EVERY', 16))


def batch_row_wall_s() -> float:
    """Per-row retry wall: how long a row keeps retrying through LB
    restarts / replica failovers before the job counts it failed."""
    return _f('SKYTPU_BATCH_ROW_WALL_S', 90.0)
