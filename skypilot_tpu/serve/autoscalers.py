"""Autoscalers: turn request rates + replica state into scaling decisions.

Parity: sky/serve/autoscalers.py — Autoscaler base (:57),
RequestRateAutoscaler (:145: target = ceil(QPS / target_qps_per_replica)
with upscale/downscale hysteresis windows :243), and
FallbackRequestRateAutoscaler (:480: base on-demand replicas + dynamic
fallback while spot replicas recover).

Pure decision logic — no I/O — so the decision table is unit-testable
exactly like the reference's tests/test_serve_autoscaler.py.
"""
import dataclasses
import enum
import math
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.serve import constants
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec


class DecisionOperator(enum.Enum):
    SCALE_UP = 'scale_up'
    SCALE_DOWN = 'scale_down'


@dataclasses.dataclass
class AutoscalerDecision:
    operator: DecisionOperator
    # SCALE_UP: {'use_spot': bool}; SCALE_DOWN: {'replica_id': int}.
    target: Dict[str, Any]


@dataclasses.dataclass
class ReplicaView:
    """The slice of replica state the autoscaler needs."""
    replica_id: int
    status: ReplicaStatus
    version: int
    is_spot: bool
    # Fed from the LB's sync payload (replica_draining/replica_inflight)
    # so scale-down prefers replicas that are already draining and
    # avoids killing in-flight work.
    draining: bool = False
    inflight: int = 0

    @property
    def alive(self) -> bool:
        """Counts toward capacity (launching or serving)."""
        return not self.status.is_failed() and (
            self.status != ReplicaStatus.SHUTTING_DOWN)


class Autoscaler:
    """Base: fixed replica count (spec.min_replicas)."""

    def __init__(self, spec: SkyTpuServiceSpec):
        self.spec = spec
        self.latest_version = 1

    @classmethod
    def make(cls, spec: SkyTpuServiceSpec) -> 'Autoscaler':
        if not spec.autoscaling_enabled:
            return Autoscaler(spec)
        if spec.slo_ttft_ms is not None:
            return SloLatencyAutoscaler(spec)
        if (spec.use_ondemand_fallback or
                spec.base_ondemand_fallback_replicas > 0):
            return FallbackRequestRateAutoscaler(spec)
        return RequestRateAutoscaler(spec)

    def update_spec(self, spec: SkyTpuServiceSpec, version: int) -> None:
        self.spec = spec
        self.latest_version = version

    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        pass

    def collect_latency_information(
            self, replica_latency: Dict[str, Any]) -> None:
        """LB-measured per-replica latency summaries ({url:
        {'ttft_p50_ms', 'ttft_p95_ms', 'count'}}), shipped on every
        controller sync.  Base: ignored."""
        pass

    def evaluate_scaling(
            self, replicas: List[ReplicaView]) -> List[AutoscalerDecision]:
        alive = [r for r in replicas if r.alive]
        target = self.spec.min_replicas
        decisions: List[AutoscalerDecision] = []
        if len(alive) < target:
            decisions.extend(
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': False})
                for _ in range(target - len(alive)))
        elif len(alive) > target:
            for r in _scale_down_order(alive, self.latest_version):
                if len(alive) - len(decisions) <= target:
                    break
                decisions.append(
                    AutoscalerDecision(DecisionOperator.SCALE_DOWN,
                                       {'replica_id': r.replica_id}))
        # Old-version replicas beyond the target are replaced by the
        # replica manager's rolling update, not by the autoscaler.
        return decisions


def _scale_down_order(replicas: List[ReplicaView],
                      latest_version: int) -> List[ReplicaView]:
    """Prefer terminating old versions, then already-draining, then
    unready, then least-loaded, then newest-launched (parity:
    sky/serve/autoscalers.py:285,317).  Draining/inflight default to
    False/0, reducing to the classic order when the controller has no
    LB load data."""

    def key(r: ReplicaView):
        return (
            r.version >= latest_version,            # old versions first
            not r.draining,                         # draining first:
                                                    # already off rotation
            r.status == ReplicaStatus.READY,        # unready before ready
            r.inflight,                             # idle before loaded
            -r.replica_id,                          # newest first
        )

    return sorted(replicas, key=key)


class RequestRateAutoscaler(Autoscaler):
    """target = ceil(qps / target_qps_per_replica), clamped to
    [min_replicas, max_replicas], applied only after the request rate has
    stayed over/under the threshold for upscale/downscale delay seconds."""

    def __init__(self, spec: SkyTpuServiceSpec):
        super().__init__(spec)
        self.request_timestamps: List[float] = []
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    # Test hook: timestamps are wall-clock; tests inject fake ones.
    def _now(self) -> float:
        return time.time()  # det-ok: this IS the clock seam tests patch

    def collect_request_information(
            self, request_timestamps: List[float]) -> None:
        self.request_timestamps.extend(request_timestamps)
        cutoff = self._now() - constants.qps_window_seconds()
        self.request_timestamps = [
            t for t in self.request_timestamps if t > cutoff
        ]

    def current_qps(self) -> float:
        return len(self.request_timestamps) / constants.qps_window_seconds()

    def _raw_target(self) -> int:
        assert self.spec.target_qps_per_replica is not None
        target = math.ceil(
            self.current_qps() / self.spec.target_qps_per_replica)
        lo = self.spec.min_replicas
        hi = self.spec.max_replicas
        assert hi is not None
        return max(lo, min(hi, target))

    def _desired_with_hysteresis(self, num_alive: int) -> int:
        """Move toward _raw_target only after the pressure has persisted."""
        now = self._now()
        raw = self._raw_target()
        if raw > num_alive:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.spec.upscale_delay_seconds:
                return raw
            return num_alive
        if raw < num_alive:
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if (now - self._downscale_since >=
                    self.spec.downscale_delay_seconds):
                return raw
            return num_alive
        self._upscale_since = None
        self._downscale_since = None
        return num_alive

    def evaluate_scaling(
            self, replicas: List[ReplicaView]) -> List[AutoscalerDecision]:
        alive = [r for r in replicas if r.alive]
        # Below min_replicas is never subject to hysteresis: replace
        # failed/preempted replicas immediately.
        if len(alive) < self.spec.min_replicas:
            return [
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': False})
                for _ in range(self.spec.min_replicas - len(alive))
            ]
        desired = self._desired_with_hysteresis(len(alive))
        if desired > len(alive):
            self._upscale_since = None
            return [
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': False})
                for _ in range(desired - len(alive))
            ]
        if desired < len(alive):
            self._downscale_since = None
            n_down = len(alive) - desired
            order = _scale_down_order(alive, self.latest_version)
            return [
                AutoscalerDecision(DecisionOperator.SCALE_DOWN,
                                   {'replica_id': r.replica_id})
                for r in order[:n_down]
            ]
        return []


class SloLatencyAutoscaler(Autoscaler):
    """Scale to a latency SLO instead of a QPS proxy: the LB measures
    per-replica TTFT at the relay (first SSE event / buffered
    completion) and ships rolling-window percentiles on every
    controller sync; this autoscaler holds the fleet's WORST replica
    p95 under `spec.slo_ttft_ms`.

    Target tracking is deliberately +-1 step-and-observe (not a ratio
    jump like ceil(qps/target)): TTFT is a queueing-dominated,
    nonlinear function of fleet size, so the controller steps, lets
    the window refill, and re-evaluates.  Hysteresis mirrors the
    request-rate autoscaler: breach must persist for
    upscale_delay_seconds before +1; downscale additionally requires
    p95 under slo * slo_downscale_factor (a comfort band, not just
    "under SLO") for downscale_delay_seconds before -1."""

    def __init__(self, spec: SkyTpuServiceSpec):
        super().__init__(spec)
        # Latest per-replica summary from the LB; replaced wholesale
        # each sync (the LB owns the rolling window).
        self.replica_latency: Dict[str, Any] = {}
        # Batch-plane backlog from the controller's coordinator
        # (serve/batch.py backlog()): rows remaining, tightest
        # completion window, and the measured completion rate.
        self.batch_backlog: Optional[Dict[str, Any]] = None
        self._upscale_since: Optional[float] = None
        self._downscale_since: Optional[float] = None

    # Test hook: tests drive scaling decisions with an injected clock.
    def _now(self) -> float:
        return time.time()  # det-ok: this IS the clock seam tests patch

    def collect_latency_information(
            self, replica_latency: Dict[str, Any]) -> None:
        if isinstance(replica_latency, dict):
            self.replica_latency = {
                str(u): row for u, row in replica_latency.items()
                if isinstance(row, dict)}

    def collect_batch_backlog(
            self, backlog: Optional[Dict[str, Any]]) -> None:
        self.batch_backlog = backlog if isinstance(backlog, dict) \
            else None

    def _batch_meets_window(self, n_replicas: int,
                            n_now: int) -> bool:
        """Would a fleet of ``n_replicas`` finish the batch backlog
        inside its completion window?  Projection sizes work to the
        MEASURED completion rate (rows/s at the current fleet size,
        scaled linearly); with a backlog but no rate signal yet the
        answer is pessimistic — pressure until measured otherwise."""
        b = self.batch_backlog or {}
        rows = b.get('rows_remaining') or 0
        if rows <= 0 or n_replicas <= 0:
            return True                # nothing to finish
        window = b.get('window_remaining_s')
        if window is None:
            return True
        if window <= 0.0:
            return False               # already blown: all hands
        rate = b.get('rows_per_s')
        if not isinstance(rate, (int, float)) or rate <= 0.0 or \
                n_now <= 0:
            return False
        per_replica = float(rate) / n_now
        return rows / (per_replica * n_replicas) <= window

    def fleet_ttft_p95_ms(self) -> Optional[float]:
        """Worst replica p95 (the SLO is per-request, so the slowest
        replica is the binding one), or None with no samples yet."""
        worst = None
        for row in self.replica_latency.values():
            v = row.get('ttft_p95_ms')
            if isinstance(v, (int, float)):
                worst = float(v) if worst is None else max(
                    worst, float(v))
        return worst

    def evaluate_scaling(
            self, replicas: List[ReplicaView]) -> List[AutoscalerDecision]:
        alive = [r for r in replicas if r.alive]
        lo, hi = self.spec.min_replicas, self.spec.max_replicas
        assert hi is not None       # enforced by spec validation
        if len(alive) < lo:
            # Below floor: replace immediately, no hysteresis.
            return [
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': False})
                for _ in range(lo - len(alive))
            ]
        assert self.spec.slo_ttft_ms is not None
        slo = self.spec.slo_ttft_ms
        p95 = self.fleet_ttft_p95_ms()
        now = self._now()
        # Batch backlog term (ISSUE 20): a completion window the
        # current fleet cannot meet is upscale pressure too — but only
        # while interactive TTFT holds its SLO (an interactive breach
        # already drives the first branch; batch never outranks it).
        slo_breach = p95 is not None and p95 > slo
        interactive_ok = p95 is None or p95 <= slo
        backlog_pressure = (
            interactive_ok and
            not self._batch_meets_window(len(alive), len(alive)))
        if (slo_breach or backlog_pressure) and len(alive) < hi:
            self._downscale_since = None
            if self._upscale_since is None:
                self._upscale_since = now
            if now - self._upscale_since >= self.spec.upscale_delay_seconds:
                self._upscale_since = None
                return [AutoscalerDecision(DecisionOperator.SCALE_UP,
                                           {'use_spot': False})]
            return []
        if (p95 is not None and len(alive) > lo and
                p95 < slo * constants.slo_downscale_factor() and
                # Drain batch capacity first: shrink only while the
                # SMALLER fleet still meets the completion window —
                # the batch surplus goes before the window is at risk.
                self._batch_meets_window(len(alive) - 1, len(alive))):
            self._upscale_since = None
            if self._downscale_since is None:
                self._downscale_since = now
            if (now - self._downscale_since >=
                    self.spec.downscale_delay_seconds):
                self._downscale_since = None
                victim = _scale_down_order(alive, self.latest_version)[0]
                return [AutoscalerDecision(DecisionOperator.SCALE_DOWN,
                                           {'replica_id':
                                            victim.replica_id})]
            return []
        # In band (or no signal yet): hold, reset pressure timers.
        self._upscale_since = None
        self._downscale_since = None
        return []


class FallbackRequestRateAutoscaler(RequestRateAutoscaler):
    """Spot replicas carry the request-rate target; a fixed base of
    on-demand replicas (base_ondemand_fallback_replicas) always runs, and
    while spot replicas are recovering from preemption, extra on-demand
    fallbacks fill the gap (dynamic fallback)."""

    def evaluate_scaling(
            self, replicas: List[ReplicaView]) -> List[AutoscalerDecision]:
        alive = [r for r in replicas if r.alive]
        spot = [r for r in alive if r.is_spot]
        ondemand = [r for r in alive if not r.is_spot]
        decisions: List[AutoscalerDecision] = []

        # Spot fleet follows the request rate (hysteresis as in the base).
        if len(spot) < self.spec.min_replicas:
            desired_spot = self.spec.min_replicas
        else:
            desired_spot = self._desired_with_hysteresis(len(spot))
        if desired_spot > len(spot):
            self._upscale_since = None
            decisions.extend(
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': True})
                for _ in range(desired_spot - len(spot)))
        elif desired_spot < len(spot):
            self._downscale_since = None
            order = _scale_down_order(spot, self.latest_version)
            decisions.extend(
                AutoscalerDecision(DecisionOperator.SCALE_DOWN,
                                   {'replica_id': r.replica_id})
                for r in order[:len(spot) - desired_spot])

        # On-demand: base + dynamic fallback for not-yet-READY spot.
        base = self.spec.base_ondemand_fallback_replicas
        desired_ondemand = base
        if self.spec.use_ondemand_fallback:
            spot_ready = sum(
                1 for r in spot if r.status == ReplicaStatus.READY)
            desired_ondemand = base + max(0, desired_spot - spot_ready)
        if desired_ondemand > len(ondemand):
            decisions.extend(
                AutoscalerDecision(DecisionOperator.SCALE_UP,
                                   {'use_spot': False})
                for _ in range(desired_ondemand - len(ondemand)))
        elif desired_ondemand < len(ondemand):
            order = _scale_down_order(ondemand, self.latest_version)
            decisions.extend(
                AutoscalerDecision(DecisionOperator.SCALE_DOWN,
                                   {'replica_id': r.replica_id})
                for r in order[:len(ondemand) - desired_ondemand])
        return decisions
