"""Replica manager: launches, probes, and terminates replica clusters.

Parity: sky/serve/replica_managers.py — SkyPilotReplicaManager (:610) with
scale_up → recursive `launch()` (:58), scale_down → cluster teardown
(:140), and the three daemon loops (process-pool refresher :951, job
status fetcher :967, readiness prober :1030 with consecutive-failure
counting :493) folded into the controller's tick (run_once) so the control
flow is deterministic and testable.
"""
import concurrent.futures
import json
import os
import threading
import time
import traceback
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from skypilot_tpu import logsys
from skypilot_tpu.serve import constants, serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec
from skypilot_tpu.task import Task

logger = logsys.init_logger(__name__)


def replica_cluster_name(service_name: str, replica_id: int) -> str:
    return f'{service_name}-{replica_id}'


class ReplicaManager:
    """Owns every replica of one service (runs on the controller host)."""

    def __init__(self, service_name: str, spec: SkyTpuServiceSpec,
                 task_yaml: str, version: int = 1):
        self.service_name = service_name
        self.spec = spec
        self.task_yaml = task_yaml
        self.version = version
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f'replica-{service_name}')
        self._inflight: Dict[int, concurrent.futures.Future] = {}

    def update_version(self, spec: SkyTpuServiceSpec, task_yaml: str,
                       version: int) -> None:
        self.spec = spec
        self.task_yaml = task_yaml
        self.version = version

    # ------------------------------------------------------------- scaling

    def scale_up(self, use_spot: bool = False) -> int:
        rid = serve_state.next_replica_id(self.service_name)
        cluster = replica_cluster_name(self.service_name, rid)
        serve_state.add_replica(self.service_name, rid, self.version,
                                cluster, use_spot)
        self._inflight[rid] = self._pool.submit(self._launch_replica, rid,
                                                cluster, use_spot)
        logger.info('[%s] scale_up -> replica %d (%s, spot=%s)',
                    self.service_name, rid, cluster, use_spot)
        return rid

    def scale_down(self, replica_id: int, purge: bool = True,
                   final_status: Optional[ReplicaStatus] = None) -> None:
        """Tear the replica cluster down.  With purge=True the record is
        removed; otherwise it is kept and left in ``final_status`` (a
        failed status) so `serve status` shows why the replica died."""
        rec = serve_state.get_replica(self.service_name, replica_id)
        if rec is None or rec['status'] == (
                ReplicaStatus.SHUTTING_DOWN.value):
            return
        serve_state.set_replica_status(self.service_name, replica_id,
                                       ReplicaStatus.SHUTTING_DOWN)
        # If this replica's launch is still in flight, the teardown must
        # wait for it: tearing down mid-provision would leave the cluster
        # the launch thread finishes creating untracked and running.
        launch_future = self._inflight.get(replica_id)
        self._inflight[replica_id] = self._pool.submit(
            self._terminate_replica, replica_id, rec['cluster_name'], purge,
            final_status, launch_future, rec.get('endpoint'))
        logger.info('[%s] scale_down replica %d', self.service_name,
                    replica_id)

    def _replica_port(self, replica_id: int, cloud: Optional[str]) -> int:
        # On the local provider every replica shares 127.0.0.1, so ports
        # must be unique per replica; real clouds give unique IPs.
        if cloud == 'local':
            return self.spec.port + replica_id
        return self.spec.port

    def _build_replica_task(self, replica_id: int, use_spot: bool) -> Task:
        import yaml
        with open(os.path.expanduser(self.task_yaml),
                  encoding='utf-8') as f:
            cfg = yaml.safe_load(f)
        cfg.pop('service', None)
        task = Task.from_yaml_config(cfg)
        resources = list(task.resources)
        if use_spot:
            task.set_resources([r.copy(use_spot=True) for r in resources])
        cloud = resources[0].cloud if resources else None
        port = self._replica_port(replica_id, cloud)
        envs = {
            'SKYTPU_SERVE_REPLICA_ID': str(replica_id),
            'SKYTPU_SERVE_REPLICA_PORT': str(port),
        }
        tp_size = resources[0].tp_size if resources else None
        if tp_size is not None and tp_size > 1:
            # The inference server reads this as its --tensor-parallel
            # default, so tp replicas shard without the task YAML having
            # to thread the flag into its run command.
            envs['SKYTPU_SERVE_TP_SIZE'] = str(tp_size)
        # Scale-up replicas boot deterministic-warm: the server's
        # --warmup default reads this, compiling every enumerated jit
        # root×bucket shape before declaring ready, so the first
        # request a fresh replica serves already runs at steady-state
        # TTFT (no compile storm behind live traffic).
        envs['SKYTPU_SERVE_WARMUP'] = '1'
        task.update_envs(envs)
        return task

    def _launch_replica(self, replica_id: int, cluster: str,
                        use_spot: bool) -> None:
        from skypilot_tpu import execution, state
        try:
            task = self._build_replica_task(replica_id, use_spot)
            execution.launch(task, cluster_name=cluster, detach_run=True,
                             stream_logs=False, retry_until_up=False)
            record = state.get_cluster_from_name(cluster)
            assert record is not None, cluster
            info = record['handle'].cluster_info()
            resources = list(task.resources)
            cloud = resources[0].cloud if resources else None
            port = self._replica_port(replica_id, cloud)
            ip = info.head.external_ip or info.head.internal_ip
            serve_state.set_replica_endpoint(self.service_name, replica_id,
                                            f'http://{ip}:{port}')
            # CAS: if scale_down won the race while we were launching, the
            # record is SHUTTING_DOWN and must stay that way (the queued
            # _terminate_replica owns it now).
            serve_state.set_replica_status(
                self.service_name, replica_id, ReplicaStatus.STARTING,
                unless=ReplicaStatus.SHUTTING_DOWN)
        except Exception as e:  # pylint: disable=broad-except
            logger.error('[%s] replica %d launch failed: %s',
                         self.service_name, replica_id, e)
            logger.debug('%s', traceback.format_exc())
            serve_state.set_replica_status(
                self.service_name, replica_id,
                ReplicaStatus.FAILED_PROVISION, str(e),
                unless=ReplicaStatus.SHUTTING_DOWN)

    def _drain_replica(self, endpoint: str) -> None:
        """Graceful drain before teardown: ask the replica to stop
        admitting and wait (bounded) for its in-flight requests to
        finish, so scale-down never kills work mid-generation.  Any
        error — replica without /drain, already-dead process — skips
        straight to teardown."""
        deadline = constants.drain_timeout()
        req = urllib.request.Request(
            endpoint + '/drain',
            data=json.dumps({'deadline_s': deadline}).encode(),
            headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=2) as r:
                if not 200 <= r.status < 300:
                    return
        except (urllib.error.URLError, OSError, ValueError):
            return
        t0 = time.time()  # det-ok: teardown-drain wait (harness-side)
        while time.time() - t0 < deadline:  # det-ok: same wait loop
            try:
                with urllib.request.urlopen(endpoint + '/healthz',
                                            timeout=2) as r:
                    doc = json.loads(r.read())
            except urllib.error.HTTPError as e:
                # /healthz answers 503 while draining — the body still
                # carries the health document.
                try:
                    doc = json.loads(e.read())
                except (ValueError, OSError):
                    return
            except (urllib.error.URLError, OSError, ValueError):
                return   # replica went away: nothing left to wait on
            if not isinstance(doc, dict) or not doc.get('draining') or \
                    doc.get('drained') or doc.get('inflight', 0) == 0:
                return
            time.sleep(0.2)

    def _terminate_replica(self, replica_id: int, cluster: str,
                           purge: bool,
                           final_status: Optional[ReplicaStatus] = None,
                           launch_future: Optional[
                               concurrent.futures.Future] = None,
                           endpoint: Optional[str] = None) -> None:
        from skypilot_tpu import core
        if launch_future is not None:
            concurrent.futures.wait([launch_future])
        if endpoint:
            self._drain_replica(endpoint)
        try:
            core.down(cluster, purge=True)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('[%s] teardown of %s: %s', self.service_name,
                           cluster, e)
        if purge:
            serve_state.remove_replica(self.service_name, replica_id)
        elif final_status is not None:
            # Restore the failure status that triggered the teardown (the
            # failure_reason column was set before scale_down and is kept).
            serve_state.set_replica_status(self.service_name, replica_id,
                                           final_status)

    def terminate_all(self) -> None:
        """Service teardown: bring down every replica cluster."""
        for rec in serve_state.get_replicas(self.service_name):
            if rec['status'] != ReplicaStatus.SHUTTING_DOWN.value:
                self.scale_down(rec['replica_id'], purge=True)
        self._pool.shutdown(wait=True)

    def busy(self) -> bool:
        self._reap()
        return bool(self._inflight)

    def _reap(self) -> None:
        done = [rid for rid, f in self._inflight.items() if f.done()]
        for rid in done:
            self._inflight.pop(rid)

    # ------------------------------------------------------------- probing

    def probe_replica(self, rec: dict) -> bool:
        """One readiness probe; returns probe success."""
        endpoint = rec.get('endpoint')
        if not endpoint:
            return False
        url = endpoint + self.spec.readiness_path
        data = None
        headers = dict(self.spec.readiness_headers or {})
        if self.spec.post_data is not None:
            data = (self.spec.post_data if isinstance(
                self.spec.post_data, (bytes, str)) else json.dumps(
                    self.spec.post_data))
            if isinstance(data, str):
                data = data.encode()
            headers.setdefault('Content-Type', 'application/json')
        req = urllib.request.Request(url, data=data, headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=self.spec.readiness_timeout_seconds) as r:
                return 200 <= r.status < 300
        except (urllib.error.URLError, OSError, ValueError):
            return False

    def probe_all(self) -> None:
        """Probe STARTING/READY/NOT_READY replicas, advance their status.

        Parity: the _replica_prober loop (replica_managers.py:1030).
        """
        now = time.time()  # det-ok: probe bookkeeping; tests drive ticks
        for rec in serve_state.get_replicas(self.service_name):
            status = ReplicaStatus(rec['status'])
            if status not in (ReplicaStatus.STARTING, ReplicaStatus.READY,
                              ReplicaStatus.NOT_READY):
                continue
            ok = self.probe_replica(rec)
            rid = rec['replica_id']
            if ok:
                if status != ReplicaStatus.READY:
                    logger.info('[%s] replica %d is READY',
                                self.service_name, rid)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.READY)
                continue
            if status == ReplicaStatus.STARTING:
                launched = rec.get('launched_at') or now
                if now - launched > self.spec.initial_delay_seconds:
                    logger.warning(
                        '[%s] replica %d failed initial delay (%ds)',
                        self.service_name, rid,
                        self.spec.initial_delay_seconds)
                    serve_state.set_replica_status(
                        self.service_name, rid,
                        ReplicaStatus.FAILED_INITIAL_DELAY,
                        'readiness probe never passed within '
                        'initial_delay_seconds')
                    self.scale_down(
                        rid, purge=False,
                        final_status=ReplicaStatus.FAILED_INITIAL_DELAY)
                continue
            failures = serve_state.bump_replica_failures(
                self.service_name, rid)
            if failures >= 2 * constants.PROBE_FAILURE_THRESHOLD:
                # NOT_READY never recovered: give up and replace it (the
                # failed record is no longer `alive`, so the autoscaler
                # launches a replacement on its next tick).
                logger.warning('[%s] replica %d failed probing (%d '
                               'consecutive failures); replacing',
                               self.service_name, rid, failures)
                serve_state.set_replica_status(
                    self.service_name, rid, ReplicaStatus.FAILED_PROBING,
                    f'readiness probe failed {failures} times in a row '
                    'after the replica had been READY')
                self.scale_down(rid, purge=False,
                                final_status=ReplicaStatus.FAILED_PROBING)
            elif failures >= constants.PROBE_FAILURE_THRESHOLD:
                logger.warning('[%s] replica %d NOT_READY (%d failures)',
                               self.service_name, rid, failures)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.NOT_READY)

    # ---------------------------------------------------------- job status

    def check_replica_clusters(self) -> None:
        """Detect preempted/externally-terminated replica clusters and
        failed replica jobs (parity: _job_status_fetcher :967 +
        _handle_preemption :784)."""
        from skypilot_tpu import backend_utils, core, state
        from skypilot_tpu.status_lib import ClusterStatus
        for rec in serve_state.get_replicas(self.service_name):
            status = ReplicaStatus(rec['status'])
            if status in (ReplicaStatus.PENDING, ReplicaStatus.PROVISIONING,
                          ReplicaStatus.SHUTTING_DOWN) or \
                    status.is_terminal():
                continue
            cluster = rec['cluster_name']
            rid = rec['replica_id']
            try:
                record = backend_utils.refresh_cluster_record(cluster)
            except Exception:  # pylint: disable=broad-except
                record = state.get_cluster_from_name(cluster)
            if record is None or record['status'] != ClusterStatus.UP:
                logger.warning('[%s] replica %d cluster %s is gone '
                               '(preempted?)', self.service_name, rid,
                               cluster)
                serve_state.set_replica_status(self.service_name, rid,
                                               ReplicaStatus.PREEMPTED)
                self.scale_down(rid, purge=True)
                continue
            # Replica job failed => replica FAILED (kept for status).
            try:
                jobs = core.queue(cluster)
            except Exception:  # pylint: disable=broad-except
                continue
            if any(j['status'] in ('FAILED', 'FAILED_SETUP')
                   for j in jobs):
                logger.warning('[%s] replica %d job failed',
                               self.service_name, rid)
                serve_state.set_replica_status(
                    self.service_name, rid, ReplicaStatus.FAILED,
                    'replica job failed')
                self.scale_down(rid, purge=False,
                                final_status=ReplicaStatus.FAILED)


class LoadBalancerSupervisor:
    """Supervise the load balancer like a replica (PR 18).

    The LB is the one single point of failure in the serve plane: every
    replica has a prober and a replacement path, but a dead LB used to
    mean a dead service until a human noticed.  This supervisor closes
    that gap with the same probe-count-restart shape the replicas get:

    - ``make_lb`` is an injected factory returning a fresh LB object
      (duck-typed: ``.run()`` blocks, ``.stop()`` shuts down, ``.port``
      for the probe URL).  Re-running the factory on restart is what
      re-adopts the warm-restart journal — adoption lives in the LB
      constructor, not here.
    - the probe hits ``/lb/stats`` (any HTTP answer = alive); after
      ``lb_restart_threshold`` consecutive failures the old incarnation
      is stopped and a new one started on the SAME port, so replica
      URLs handed to clients stay stable across LB generations.

    Deterministic seam: ``poll_once()`` is public, so tests drive the
    fail-count-restart machinery step by step without a sleep."""

    def __init__(self,
                 make_lb: Callable[[], object],
                 host: str = '127.0.0.1',
                 restart_threshold: Optional[int] = None,
                 probe_timeout: float = 2.0):
        self._make_lb = make_lb
        self._host = host
        self._threshold = (constants.lb_restart_threshold()
                           if restart_threshold is None
                           else int(restart_threshold))
        self._probe_timeout = probe_timeout
        self._stop = threading.Event()
        self.lb = make_lb()
        self._lb_thread: Optional[threading.Thread] = None
        self.restarts = 0
        self.consecutive_failures = 0

    # ---------------------------------------------------------- lifecycle

    def _spawn(self) -> None:
        self._lb_thread = threading.Thread(
            target=self.lb.run, daemon=True,
            name=f'lb-gen{self.restarts}')
        self._lb_thread.start()

    def start(self) -> None:
        """Start the LB thread + the background probe loop."""
        self._spawn()
        threading.Thread(target=self._probe_loop, daemon=True,
                         name='lb-supervisor').start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.lb.stop()
        except Exception:  # pylint: disable=broad-except
            pass

    # ------------------------------------------------------------- probing

    def _probe(self) -> bool:
        url = f'http://{self._host}:{self.lb.port}/lb/stats'
        try:
            with urllib.request.urlopen(
                    url, timeout=self._probe_timeout) as resp:
                resp.read()
            return True
        except urllib.error.HTTPError:
            # A status code — any status code — proves a live process;
            # an unhappy LB is the LB's problem, not the supervisor's.
            return True
        except Exception:  # pylint: disable=broad-except
            # Only a connection-level failure (refused/reset/timeout)
            # lands here — the LB process/thread is gone or wedged.
            return False

    def poll_once(self) -> bool:
        """One supervision step: probe, count, maybe restart.  Returns
        True iff a restart happened this step."""
        if self._probe():
            self.consecutive_failures = 0
            return False
        self.consecutive_failures += 1
        if self.consecutive_failures < self._threshold:
            return False
        logger.warning('LB failed %d consecutive probes; restarting on '
                       'port %d', self.consecutive_failures, self.lb.port)
        self.restart()
        return True

    def restart(self) -> None:
        """Tear down the current LB incarnation and start a fresh one on
        the same port (journal re-adoption happens in the factory)."""
        try:
            self.lb.stop()
        except Exception:  # pylint: disable=broad-except
            pass
        if self._lb_thread is not None:
            self._lb_thread.join(timeout=5.0)
        self.restarts += 1
        self.consecutive_failures = 0
        self.lb = self._make_lb()
        self._spawn()

    def _probe_loop(self) -> None:
        interval = constants.lb_health_probe_interval()
        while not self._stop.is_set():
            self._stop.wait(interval)
            if self._stop.is_set():
                return
            try:
                self.poll_once()
            except Exception as e:  # pylint: disable=broad-except
                logger.error('LB supervisor step failed: %s', e,
                             exc_info=True)

    def stats(self) -> dict:
        return {
            'restarts': self.restarts,
            'consecutive_probe_failures': self.consecutive_failures,
            'alive': (self._lb_thread is not None and
                      self._lb_thread.is_alive()),
        }
