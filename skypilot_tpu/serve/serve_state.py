"""Serve-plane state: SQLite on the controller host.

Parity: sky/serve/serve_state.py — `services` + `replicas` tables with the
ReplicaStatus (:83) and ServiceStatus (:175) machines.  Replica records are
JSON (not pickles): the row must be readable by codegen snippets running
under a different interpreter than the controller process.
"""
import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

_DB_PATH = '~/.skytpu/serve/state.db'


class ReplicaStatus(enum.Enum):
    """Parity: sky/serve/serve_state.py:83."""
    PENDING = 'PENDING'
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'            # provisioned, probe not yet passing
    READY = 'READY'
    NOT_READY = 'NOT_READY'          # probe failing after having been READY
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'                # job failed on the replica
    FAILED_INITIAL_DELAY = 'FAILED_INITIAL_DELAY'
    FAILED_PROBING = 'FAILED_PROBING'
    FAILED_PROVISION = 'FAILED_PROVISION'
    PREEMPTED = 'PREEMPTED'

    def is_failed(self) -> bool:
        return self in _REPLICA_FAILED

    def is_terminal(self) -> bool:
        return self in _REPLICA_FAILED

    def is_scale_down_candidate(self) -> bool:
        return self not in (ReplicaStatus.SHUTTING_DOWN,)


_REPLICA_FAILED = {
    ReplicaStatus.FAILED, ReplicaStatus.FAILED_INITIAL_DELAY,
    ReplicaStatus.FAILED_PROBING, ReplicaStatus.FAILED_PROVISION
}


class ServiceStatus(enum.Enum):
    """Parity: sky/serve/serve_state.py:175."""
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'    # no READY replica yet, some in flight
    CONTROLLER_FAILED = 'CONTROLLER_FAILED'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    NO_REPLICA = 'NO_REPLICA'

    @classmethod
    def from_replica_statuses(
            cls, statuses: List[ReplicaStatus]) -> 'ServiceStatus':
        if any(s == ReplicaStatus.READY for s in statuses):
            return cls.READY
        if any(s.is_failed() for s in statuses):
            return cls.FAILED
        if not statuses:
            return cls.NO_REPLICA
        return cls.REPLICA_INIT


def _db() -> sqlite3.Connection:
    path = os.path.expanduser(_DB_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite3.connect(path, timeout=10.0)
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute("""CREATE TABLE IF NOT EXISTS services (
        name TEXT PRIMARY KEY,
        status TEXT,
        controller_port INTEGER,
        load_balancer_port INTEGER,
        policy TEXT,
        spec TEXT,
        task_yaml TEXT,
        version INTEGER DEFAULT 1,
        controller_pid INTEGER,
        created_at REAL)""")
    conn.execute("""CREATE TABLE IF NOT EXISTS replicas (
        service_name TEXT,
        replica_id INTEGER,
        status TEXT,
        version INTEGER,
        cluster_name TEXT,
        endpoint TEXT,
        is_spot INTEGER DEFAULT 0,
        launched_at REAL,
        ready_at REAL,
        consecutive_failures INTEGER DEFAULT 0,
        failure_reason TEXT,
        PRIMARY KEY (service_name, replica_id))""")
    conn.commit()
    return conn


# ------------------------------------------------------------------ services


def add_service(name: str, controller_port: int, lb_port: int,
                policy: str, spec_json: str, task_yaml: str,
                controller_pid: int) -> bool:
    """Returns False if the service already exists."""
    try:
        with _db() as conn:
            conn.execute(
                'INSERT INTO services (name, status, controller_port, '
                'load_balancer_port, policy, spec, task_yaml, '
                'controller_pid, created_at) VALUES (?,?,?,?,?,?,?,?,?)',
                (name, ServiceStatus.CONTROLLER_INIT.value, controller_port,
                 lb_port, policy, spec_json, task_yaml, controller_pid,
                 time.time()))  # det-ok: created_at DB stamp
        return True
    except sqlite3.IntegrityError:
        return False


def remove_service(name: str) -> None:
    with _db() as conn:
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _db() as conn:
        conn.execute('UPDATE services SET status=? WHERE name=?',
                     (status.value, name))


def set_service_spec(name: str, spec_json: str, task_yaml: str) -> None:
    with _db() as conn:
        conn.execute(
            'UPDATE services SET spec=?, task_yaml=?, '
            'version=version+1 WHERE name=?', (spec_json, task_yaml, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    conn = _db()
    conn.row_factory = sqlite3.Row
    row = conn.execute('SELECT * FROM services WHERE name=?',
                       (name,)).fetchone()
    return dict(row) if row else None


def get_services() -> List[Dict[str, Any]]:
    conn = _db()
    conn.row_factory = sqlite3.Row
    rows = conn.execute('SELECT * FROM services ORDER BY name').fetchall()
    return [dict(r) for r in rows]


# ------------------------------------------------------------------ replicas


def add_replica(service_name: str, replica_id: int, version: int,
                cluster_name: str, is_spot: bool) -> None:
    with _db() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id, '
            'status, version, cluster_name, is_spot, launched_at, '
            'consecutive_failures) VALUES (?,?,?,?,?,?,?,0)',
            (service_name, replica_id, ReplicaStatus.PROVISIONING.value,
             version, cluster_name, int(is_spot),
             time.time()))  # det-ok: launched_at DB stamp


def remove_replica(service_name: str, replica_id: int) -> None:
    with _db() as conn:
        conn.execute(
            'DELETE FROM replicas WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))


def set_replica_status(service_name: str, replica_id: int,
                       status: ReplicaStatus,
                       failure_reason: Optional[str] = None,
                       unless: Optional[ReplicaStatus] = None) -> bool:
    """Update a replica's status.  With `unless`, the write is an atomic
    compare-and-set that is skipped when the row currently holds that
    status (e.g. a launch completing after scale_down must not overwrite
    SHUTTING_DOWN).  Returns True iff a row was updated."""
    fields: Dict[str, Any] = {'status': status.value}
    if status == ReplicaStatus.READY:
        fields['ready_at'] = time.time()  # det-ok: ready_at DB stamp
        fields['consecutive_failures'] = 0
    if failure_reason is not None:
        fields['failure_reason'] = failure_reason[:2000]
    sets = ', '.join(f'{k}=?' for k in fields)
    where = 'WHERE service_name=? AND replica_id=?'
    args = list(fields.values()) + [service_name, replica_id]
    if unless is not None:
        where += ' AND status != ?'
        args.append(unless.value)
    with _db() as conn:
        cur = conn.execute(f'UPDATE replicas SET {sets} {where}', args)
        return cur.rowcount > 0


def set_replica_endpoint(service_name: str, replica_id: int,
                         endpoint: str) -> None:
    with _db() as conn:
        conn.execute(
            'UPDATE replicas SET endpoint=? '
            'WHERE service_name=? AND replica_id=?',
            (endpoint, service_name, replica_id))


def bump_replica_failures(service_name: str, replica_id: int) -> int:
    """Increment and return the consecutive probe failure count."""
    conn = _db()
    with conn:
        conn.execute(
            'UPDATE replicas SET consecutive_failures='
            'consecutive_failures+1 WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        row = conn.execute(
            'SELECT consecutive_failures FROM replicas '
            'WHERE service_name=? AND replica_id=?',
            (service_name, replica_id)).fetchone()
    return row[0] if row else 0


def reset_replica_failures(service_name: str, replica_id: int) -> None:
    with _db() as conn:
        conn.execute(
            'UPDATE replicas SET consecutive_failures=0 '
            'WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    conn = _db()
    conn.row_factory = sqlite3.Row
    rows = conn.execute(
        'SELECT * FROM replicas WHERE service_name=? ORDER BY replica_id',
        (service_name,)).fetchall()
    return [dict(r) for r in rows]


def get_replica(service_name: str,
                replica_id: int) -> Optional[Dict[str, Any]]:
    conn = _db()
    conn.row_factory = sqlite3.Row
    row = conn.execute(
        'SELECT * FROM replicas WHERE service_name=? AND replica_id=?',
        (service_name, replica_id)).fetchone()
    return dict(row) if row else None


def next_replica_id(service_name: str) -> int:
    row = _db().execute(
        'SELECT MAX(replica_id) FROM replicas WHERE service_name=?',
        (service_name,)).fetchone()
    return (row[0] or 0) + 1


def ready_replica_endpoints(service_name: str) -> List[str]:
    rows = _db().execute(
        'SELECT endpoint FROM replicas WHERE service_name=? AND status=? '
        'AND endpoint IS NOT NULL ORDER BY replica_id',
        (service_name, ReplicaStatus.READY.value)).fetchall()
    return [r[0] for r in rows]


# ----------------------------------------------------- status table as JSON


def services_as_json() -> str:
    out = []
    for svc in get_services():
        replicas = get_replicas(svc['name'])
        svc['replica_statuses'] = [r['status'] for r in replicas]
        svc['replicas'] = replicas
        out.append(svc)
    return json.dumps(out)
