"""Durable bulk-inference plane: journaled batch jobs with
exactly-once row accounting (ROADMAP item 4).

A batch job is a list of greedy prompts plus a completion window.  The
coordinator persists the job to an append-compacted journal (the
`serve/lb_journal.py` pattern: one JSON doc per line, torn-tail
tolerant, injected clock, fsync only on state edges), shards it into
rows dispatched as QoS ``batch``-class requests through the load
balancer, and spools each completed row to disk keyed by
``(job_id, row_idx)`` together with a content hash of the output.

The durability contract, per actor:

- **Replica dies** — the LB's failover (PR 5) reissues the in-flight
  stream; rows that were never dispatched simply stay pending.  Only
  unfinished rows are ever (re)sent.
- **LB dies and restarts** — the row transport retries connection
  errors through the outage (greedy decode is deterministic, so a
  from-scratch reissue yields identical tokens); the LB's own journal
  re-adopts the orphaned row leases for observability
  (``batch_leases_adopted`` in ``/lb/stats``).
- **Controller/coordinator dies** — a fresh coordinator on the same
  journal path resumes from the last checkpoint: completed rows are
  recognised by their ``row:`` journal docs + spool files and are
  NEVER re-run; only the unfinished remainder re-enters the queue.

Exactly-once: a replayed row (e.g. its first attempt completed but the
ack was lost to a crash) recomputes the same greedy bytes, hashes to
the same digest, and dedups against the spooled record — the
``duplicates`` counter ticks instead of a second spool write.  A
*different* hash for an already-recorded row is a determinism
violation and fails the job loudly (it would silently corrupt output
otherwise).

Journal schema (all docs carry no wall-clock timestamps; ages come
from the injected clock):

- ``job:<id>``  — the job body + lifecycle state (fsync'd on edges:
  submitted / done / failed).
- ``row:<id>:<idx>`` — ``{'hash': <sha256>}`` per completed row (the
  payload itself lives in the spool; the journal only needs the
  digest to dedup replays).
- ``ckpt:<id>`` — ``{'completed': n}`` fsync'd every
  ``batch_checkpoint_every`` rows: bounds how much a crash can force
  the coordinator to re-VERIFY (never re-run).
"""
import hashlib
import json
import os
import threading
import time
import uuid
from collections import deque
from http.client import HTTPConnection
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.serve import constants
from skypilot_tpu.serve.lb_journal import LBJournal

# Terminal row finish reasons: anything else is a failed attempt the
# transport retries inside the row wall.
_ROW_OK = ('length', 'eos')

JOB_STATES = ('running', 'done', 'failed')


def row_hash(output_tokens: List[int], finish_reason: str) -> str:
    """Content digest a replayed row must reproduce exactly."""
    doc = json.dumps([list(output_tokens), finish_reason],
                     separators=(',', ':')).encode()
    return hashlib.sha256(doc).hexdigest()


def _http_row_transport(lb_port: int) -> Callable[[dict, float], dict]:
    """Default row transport: stream a greedy row through the LB,
    retrying connection-level errors (an LB mid-restart, a severed
    stream) until the row wall expires.  Returns the terminal SSE
    event; raises after the wall."""

    def send(payload: dict, wall_s: float) -> dict:
        deadline = time.time() + wall_s   # det-ok: HTTP retry wall
        last: Optional[BaseException] = None
        while time.time() < deadline:     # det-ok: HTTP retry wall
            try:
                conn = HTTPConnection('127.0.0.1', lb_port, timeout=30)
                try:
                    conn.request(
                        'POST', '/generate',
                        body=json.dumps(payload).encode(),
                        headers={'Content-Type': 'application/json'})
                    resp = conn.getresponse()
                    if resp.status != 200:
                        raise RuntimeError(f'LB answered {resp.status}')
                    buf, events = b'', []
                    while True:
                        chunk = resp.read1(65536)
                        if not chunk:
                            break
                        buf += chunk
                        while b'\n\n' in buf:
                            ev, buf = buf.split(b'\n\n', 1)
                            for line in ev.split(b'\n'):
                                if line.startswith(b'data: '):
                                    events.append(json.loads(line[6:]))
                finally:
                    conn.close()
                done = [e for e in events if e.get('done')]
                if len(done) == 1 and \
                        done[0].get('finish_reason') in _ROW_OK:
                    return done[0]
                last = RuntimeError(
                    f'incomplete stream ({len(done)} terminal events)')
            except (OSError, RuntimeError) as e:
                last = e
            time.sleep(0.2)               # det-ok: HTTP retry backoff
        raise RuntimeError(f'row never completed: {last}')

    return send


class BatchCoordinator:
    """Owns the batch-job journal, the row dispatch pool, and the
    completed-row spool.  One coordinator per controller; the chaos
    harness also runs it standalone (the coordinator IS the
    controller-side actor the ``--batch`` leg kills and resumes)."""

    def __init__(self, journal_path: str,
                 lb_port: Optional[int] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 transport: Optional[Callable[[dict, float], dict]] = None,
                 spool_dir: Optional[str] = None,
                 row_workers: Optional[int] = None,
                 state_sink: Optional[Callable[..., None]] = None) -> None:
        if transport is None:
            if lb_port is None:
                raise ValueError('need an lb_port or an injected '
                                 'transport to dispatch rows')
            transport = _http_row_transport(lb_port)
        self._transport = transport
        self._clock = clock
        self._row_workers = row_workers or constants.batch_row_workers()
        self._ckpt_every = max(1, constants.batch_checkpoint_every())
        self._row_wall_s = constants.batch_row_wall_s()
        self.spool_dir = spool_dir or constants.batch_spool_dir() or \
            os.path.join(os.path.dirname(os.path.abspath(journal_path)),
                         'spool')
        os.makedirs(self.spool_dir, exist_ok=True)
        # state_sink(job_id, state, completed, total): thin jobs-plane
        # mirror (jobs/state.py batch_jobs table) — never on the row
        # hot path, only on lifecycle edges and checkpoints.
        self._state_sink = state_sink
        self._journal = LBJournal(journal_path, clock=clock)
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.batch._lock')
        self._jobs: Dict[str, Dict[str, Any]] = {}   # guarded-by: _lock
        self._pending: Dict[str, deque] = {}         # guarded-by: _lock
        self._inflight: Dict[str, int] = {}          # guarded-by: _lock
        self._row_attempts: Dict[Any, int] = {}      # guarded-by: _lock
        # Measured completion rate (rows/s EWMA, injected clock):
        # the autoscaler's backlog projection sizes the fleet against
        # THIS, never an assumed per-replica throughput.
        self._rows_per_s: Optional[float] = None     # guarded-by: _lock
        self._last_done_t: Optional[float] = None    # guarded-by: _lock
        # Row-retry policy belongs to the jobs plane
        # (jobs/recovery_strategy.py); lazy import keeps serve/ free
        # of the jobs plane's launch-stack imports at module load.
        from skypilot_tpu.jobs.recovery_strategy import BatchRowRecovery
        self._recovery = BatchRowRecovery()
        self._done_events: Dict[str, threading.Event] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._recover()

    # ------------------------------------------------------ lifecycle

    def submit(self, prompts: List[List[int]], max_new_tokens: int, *,
               completion_window_s: float = 3600.0,
               tenant_id: Optional[str] = None,
               temperature: Optional[float] = None,
               job_id: Optional[str] = None) -> str:
        """Accept a job.  Greedy-only: a nonzero temperature breaks
        the determinism the exactly-once contract hashes against, so
        it is a typed client error, not a silent downgrade."""
        if temperature not in (None, 0, 0.0):
            raise ValueError(
                'batch jobs are greedy-only (temperature must be 0): '
                'replay determinism is the durability contract')
        if not prompts or not all(
                isinstance(p, list) and p and
                all(isinstance(t, int) for t in p) for p in prompts):
            raise ValueError('prompts must be non-empty lists of '
                             'int token ids')
        if not isinstance(max_new_tokens, int) or max_new_tokens <= 0:
            raise ValueError('max_new_tokens must be a positive int')
        jid = job_id or uuid.uuid4().hex[:12]
        doc = {'job_id': jid, 'prompts': prompts,
               'max_new_tokens': max_new_tokens,
               'completion_window_s': float(completion_window_s),
               'tenant_id': tenant_id, 'state': 'running',
               'n_rows': len(prompts),
               'submitted_at': self._clock(),
               'duplicates': 0, 'retries': 0,
               'determinism_violations': 0}
        with self._lock:
            if jid in self._jobs:
                raise ValueError(f'job {jid!r} already exists')
            self._jobs[jid] = doc
            self._pending[jid] = deque(range(len(prompts)))
            self._inflight[jid] = 0
            self._done_events[jid] = threading.Event()
        self._journal.put(f'job:{jid}', doc, fsync=True)
        self._sink(jid, 'running', 0, len(prompts))
        self._spawn_workers(jid)
        return jid

    def _recover(self) -> None:
        """Resume from the journal: jobs still 'running' re-enter the
        queue with ONLY their unfinished rows; completed rows are
        trusted by digest (journal ``row:`` doc + spool file)."""
        snap = self._journal.snapshot()
        for key, doc in snap.items():
            if not key.startswith('job:'):
                continue
            jid = doc['job_id']
            with self._lock:
                self._jobs[jid] = doc
                self._done_events[jid] = threading.Event()
                if doc['state'] != 'running':
                    self._done_events[jid].set()
                    continue
                pending = deque(
                    i for i in range(doc['n_rows'])
                    if self._row_record(snap, jid, i) is None)
                self._pending[jid] = pending
                self._inflight[jid] = 0
            if doc['state'] == 'running':
                if pending:
                    self._spawn_workers(jid)
                else:
                    self._finish_job(jid)

    def _row_record(self, snap: dict, jid: str,
                    idx: int) -> Optional[dict]:
        """A row counts as completed only when BOTH the journal digest
        and the spool payload agree — a torn spool write re-runs the
        row (same greedy bytes, same digest)."""
        rec = snap.get(f'row:{jid}:{idx}')
        if rec is None:
            return None
        spooled = self._read_spool(jid, idx)
        if spooled is None or spooled.get('hash') != rec.get('hash'):
            return None
        return rec

    def stop(self) -> None:
        """Halt dispatch WITHOUT touching job state — the crash the
        chaos harness simulates for the controller actor.  In-flight
        rows are abandoned mid-stream; a successor coordinator on the
        same journal path re-runs only what never spooled."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        self._journal.close()

    # ------------------------------------------------------- dispatch

    def _spawn_workers(self, jid: str) -> None:
        n = min(self._row_workers,
                max(1, len(self._pending.get(jid, ()))))
        for _ in range(n):
            t = threading.Thread(target=self._worker, args=(jid,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self, jid: str) -> None:
        while not self._stop.is_set():
            with self._lock:
                job = self._jobs.get(jid)
                pending = self._pending.get(jid)
                if job is None or job['state'] != 'running' or \
                        not pending:
                    break
                idx = pending.popleft()
                self._inflight[jid] += 1
            try:
                self._run_row(jid, job, idx)
            except Exception as e:  # noqa: BLE001 — row wall expired
                backoff = 0.0
                with self._lock:
                    job['retries'] += 1
                    attempts = self._row_attempts[(jid, idx)] = \
                        self._row_attempts.get((jid, idx), 0) + 1
                    if self._stop.is_set():
                        # Crash-stop: leave the row pending for the
                        # successor, don't fail the job.
                        self._pending[jid].appendleft(idx)
                    elif not self._recovery.should_retry(
                            attempts, self._window_remaining(job)):
                        job['state'] = 'failed'
                        job['error'] = (
                            f'row {idx} unfinished after {attempts} '
                            f'attempts / past the window: {e}')
                    else:
                        self._pending[jid].append(idx)
                        backoff = self._recovery.backoff_s(attempts)
                if backoff:
                    self._stop.wait(backoff)
            finally:
                with self._lock:
                    self._inflight[jid] -= 1
            self._maybe_finish(jid)
        with self._lock:
            if self._jobs.get(jid, {}).get('state') == 'failed':
                self._done_events[jid].set()

    def _run_row(self, jid: str, job: dict, idx: int) -> None:
        payload = {'request_id': f'batch:{jid}:{idx}',
                   'tokens': job['prompts'][idx],
                   'max_new_tokens': job['max_new_tokens'],
                   'temperature': 0.0, 'stream': True,
                   'priority': 'batch'}
        if job.get('tenant_id'):
            payload['tenant_id'] = job['tenant_id']
        done = self._transport(payload, self._row_wall_s)
        self._record_row(jid, idx, list(done.get('output_tokens', [])),
                         str(done.get('finish_reason')))

    def _record_row(self, jid: str, idx: int,
                    output_tokens: List[int],
                    finish_reason: str) -> None:
        h = row_hash(output_tokens, finish_reason)
        key = f'row:{jid}:{idx}'
        with self._lock:
            job = self._jobs[jid]
            prior = self._journal.get(key)
            if prior is not None:
                if prior.get('hash') == h:
                    job['duplicates'] += 1     # exactly-once dedup
                    if self._read_spool(jid, idx) is None:
                        # Journaled digest with a torn spool write:
                        # the replay heals the payload (same bytes,
                        # same digest) without a second journal line.
                        self._write_spool(
                            jid, idx,
                            {'hash': h, 'output_tokens': output_tokens,
                             'finish_reason': finish_reason})
                    return
                job['determinism_violations'] += 1
                job['state'] = 'failed'
                job['error'] = (f'row {idx} replay hash mismatch: '
                                f'{prior.get("hash")} != {h}')
                self._journal.put(f'job:{jid}', job, fsync=True)
                self._sink(jid, 'failed', self._completed(jid),
                           job['n_rows'])
                return
            self._write_spool(jid, idx, {'hash': h,
                                         'output_tokens': output_tokens,
                                         'finish_reason': finish_reason})
            self._journal.put(key, {'hash': h})
            t = self._clock()
            if self._last_done_t is not None and t > self._last_done_t:
                r = 1.0 / (t - self._last_done_t)
                self._rows_per_s = r if self._rows_per_s is None else \
                    0.3 * r + 0.7 * self._rows_per_s
            self._last_done_t = t
            completed = self._completed(jid)
            if completed % self._ckpt_every == 0:
                self._journal.put(f'ckpt:{jid}',
                                  {'completed': completed}, fsync=True)
                self._sink(jid, 'running', completed, job['n_rows'])

    def _maybe_finish(self, jid: str) -> None:
        with self._lock:
            job = self._jobs.get(jid)
            if job is None:
                return
            if job['state'] == 'running' and \
                    not self._pending.get(jid) and \
                    self._inflight.get(jid, 0) == 0 and \
                    self._completed(jid) >= job['n_rows']:
                pass                  # fall through to finish below
            elif job['state'] == 'failed' and \
                    not self._done_events[jid].is_set():
                self._journal.put(f'job:{jid}', job, fsync=True)
                self._sink(jid, 'failed', self._completed(jid),
                           job['n_rows'])
                self._done_events[jid].set()
                return
            else:
                return
        self._finish_job(jid)

    def _finish_job(self, jid: str) -> None:
        """All rows spooled: assemble the final output file (row order,
        one JSON line per row) and fsync the 'done' edge."""
        with self._lock:
            job = self._jobs[jid]
            if job['state'] == 'done':
                return
            job['state'] = 'done'
            n = job['n_rows']
        out = self.result_path(jid)
        tmp = out + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as fh:
            for i in range(n):
                rec = self._read_spool(jid, i)
                fh.write(json.dumps(
                    {'row': i, 'hash': rec['hash'],
                     'output_tokens': rec['output_tokens'],
                     'finish_reason': rec['finish_reason']},
                    separators=(',', ':'), sort_keys=True) + '\n')
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, out)
        self._journal.put(f'job:{jid}', self._jobs[jid], fsync=True)
        self._sink(jid, 'done', n, n)
        self._done_events[jid].set()

    # -------------------------------------------------------- spool

    def _spool_path(self, jid: str, idx: int) -> str:
        d = os.path.join(self.spool_dir, jid)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f'{idx}.json')

    def result_path(self, jid: str) -> str:
        return os.path.join(self.spool_dir, f'{jid}.out.jsonl')

    def _write_spool(self, jid: str, idx: int, doc: dict) -> None:
        path = self._spool_path(jid, idx)
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as fh:
            json.dump(doc, fh, separators=(',', ':'), sort_keys=True)
        os.replace(tmp, path)

    def _read_spool(self, jid: str, idx: int) -> Optional[dict]:
        try:
            with open(self._spool_path(jid, idx),
                      encoding='utf-8') as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None

    # ------------------------------------------------------- queries

    def _completed(self, jid: str) -> int:
        # Counted off the journal (source of truth), not an in-memory
        # counter: resume and dedup both keep it honest.
        n = self._jobs[jid]['n_rows']
        return sum(1 for i in range(n)
                   if self._journal.get(f'row:{jid}:{i}') is not None)

    def _window_remaining(self, job: dict) -> float:
        return job['completion_window_s'] - \
            (self._clock() - job['submitted_at'])

    def status(self, jid: str) -> Dict[str, Any]:
        with self._lock:
            job = self._jobs.get(jid)
            if job is None:
                raise KeyError(jid)
            completed = self._completed(jid)
            return {'job_id': jid,  # wire-ok: client-facing API field
                    'state': job['state'],
                    'n_rows': job['n_rows'],  # wire-ok: client-facing API field
                    'completed': completed,
                    'pending': len(self._pending.get(jid, ())),  # wire-ok: client-facing API field
                    'inflight': self._inflight.get(jid, 0),  # wire-ok: client-facing API field
                    'duplicates': job['duplicates'],
                    'retries': job['retries'],
                    'determinism_violations':
                        job['determinism_violations'],
                    'window_remaining_s':  # wire-ok: client-facing API field
                        self._window_remaining(job),
                    'error': job.get('error')}

    def backlog(self) -> Dict[str, Any]:
        """The autoscaler's batch signal: how many rows remain across
        running jobs and how much completion window is left (the
        tightest job wins)."""
        with self._lock:
            jobs = [j for j in self._jobs.values()
                    if j['state'] == 'running']
            rows = sum(j['n_rows'] - self._completed(j['job_id'])
                       for j in jobs)
            window = min((self._window_remaining(j) for j in jobs),
                         default=None)
            return {'jobs': len(jobs), 'rows_remaining': rows,
                    'window_remaining_s': window,
                    'rows_per_s': self._rows_per_s}

    def join(self, jid: str, timeout: float = 120.0) -> bool:
        ev = self._done_events.get(jid)
        return bool(ev and ev.wait(timeout))

    def _sink(self, jid: str, state: str, completed: int,
              total: int) -> None:
        if self._state_sink is None:
            return
        try:
            self._state_sink(jid, state, completed, total)
        except Exception:  # noqa: BLE001 — the mirror must never
            pass           # take down the dispatch plane
