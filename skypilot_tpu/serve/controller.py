"""Per-service controller: autoscaler loop + HTTP API for the LB.

Parity: sky/serve/controller.py — a FastAPI app with
/controller/load_balancer_sync (:100), /controller/update_service (:116),
/controller/terminate_replica (:162) and the autoscaler thread (:64).
Ours is a stdlib ThreadingHTTPServer (no FastAPI on TPU hosts) plus a
deterministic `run_once` tick so tests can drive the control loop.
"""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu import logsys
from skypilot_tpu.serve import autoscalers, constants, serve_state
from skypilot_tpu.serve.autoscalers import DecisionOperator
from skypilot_tpu.serve.replica_managers import ReplicaManager
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.serve_utils import UpdateMode
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec

logger = logsys.init_logger(__name__)


class BatchPlaneDisabled(RuntimeError):
    """POST /v1/batches with no journal configured: a typed,
    retryable 503 (the operator may be provisioning the path)."""


class ServeController:
    """One per service; owns the autoscaler + replica manager."""

    # Class-level defaults: the batch plane is optional, and several
    # tests build bare controllers via __new__ — state_snapshot must
    # not require the batch attrs to have been wired.
    batch = None
    lb_port: Optional[int] = None

    def __init__(self, service_name: str, spec: SkyTpuServiceSpec,
                 task_yaml: str, port: int):
        self.service_name = service_name
        self.spec = spec
        self.port = port
        self.version = 1
        self.update_mode = UpdateMode.ROLLING
        # Size of the pre-update fleet, recorded when an update arrives:
        # replacement sizing and drain pacing both work against the LIVE
        # (possibly autoscaled-above-min) fleet, not min_replicas.
        self._update_old_fleet = 0
        # Crash recovery: the service record + replica rows in
        # serve_state survive a controller restart, and a restart
        # mid-update must neither forget the update (version) nor the
        # pre-update fleet size (drain pacing).  Re-adopt both here: the
        # recovered old-fleet size is old READY + latest READY — the
        # ready capacity the update is defending.  (Plugging that into
        # _update_replicas: old_drained = latest_ready so permits = 0 —
        # conservative: drains resume only as NEW replicas come ready
        # post-restart, never dropping capacity below where we rejoined.)
        svc = serve_state.get_service(service_name)
        if svc is not None:
            self.version = int(svc.get('version', 1))
            live = serve_state.get_replicas(service_name)
            old_ready = sum(
                1 for r in live if r['version'] < self.version and
                ReplicaStatus(r['status']) == ReplicaStatus.READY)
            latest_ready = sum(
                1 for r in live if r['version'] >= self.version and
                ReplicaStatus(r['status']) == ReplicaStatus.READY)
            if old_ready > 0:
                self._update_old_fleet = old_ready + latest_ready
        self.autoscaler = autoscalers.Autoscaler.make(spec)
        self.autoscaler.latest_version = self.version
        self.replica_manager = ReplicaManager(service_name, spec, task_yaml)
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._last_probe = 0.0
        self._last_cluster_check = 0.0
        # Last LB-reported per-replica load view (endpoint-url keyed),
        # folded into the autoscaler's ReplicaViews each tick.
        self._lb_lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.controller._lb_lock')
        self._lb_inflight: dict = {}  # guarded-by: _lb_lock
        self._lb_draining: set = set()  # guarded-by: _lb_lock
        # Per-replica prefix-affinity routing counters ({url: {'hits',
        # 'spills'}}), shipped by the LB when its policy exports them.
        self._lb_affinity: dict = {}  # guarded-by: _lb_lock
        # QoS plane views from the LB sync (same path as affinity):
        # per-tenant rate-limit counters and per-replica TTFT
        # percentile summaries.
        self._lb_tenant_qos: dict = {}  # guarded-by: _lb_lock
        self._lb_latency: dict = {}  # guarded-by: _lb_lock
        # Per-replica tensor degree from the LB's /healthz probes
        # (engine kv.tp): 1 = data-parallel, N = an N-chip TP replica.
        # Surfaced per replica in state_snapshot() so operators can see
        # mixed TP/DP fleet composition at a glance.
        self._lb_tp: dict = {}  # guarded-by: _lb_lock
        # Control-plane resilience views from the LB sync (PR 18):
        # replicas in gray-failure probation, retry-budget level, and
        # journal staleness — mirrored into state_snapshot() so one
        # GET /controller/state shows the whole resilience posture.
        self._lb_probation: list = []  # guarded-by: _lb_lock
        self._lb_retry_budget: Optional[float] = None  # guarded-by: _lb_lock
        self._lb_journal_age: Optional[float] = None  # guarded-by: _lb_lock
        # Set by service.py when the LB runs under a supervisor; its
        # stats() feed the state_snapshot 'load_balancer' block.
        self.lb_supervisor = None
        # Batch plane (ISSUE 20): created lazily on the first
        # POST /v1/batches — disabled (typed 503) until the operator
        # sets SKYTPU_BATCH_JOURNAL.  lb_port is set by service.py
        # once the LB is up; the coordinator dispatches rows there.
        self.batch = None
        self.lb_port: Optional[int] = None

    # ----------------------------------------------------------- HTTP API

    def _handle(self, path: str, payload: dict) -> dict:
        if path == '/controller/load_balancer_sync':
            ts: List[float] = payload.get('request_timestamps', [])
            self.autoscaler.collect_request_information(ts)
            inflight = payload.get('replica_inflight')
            draining = payload.get('replica_draining')
            affinity = payload.get('replica_affinity')
            tenant_qos = payload.get('tenant_qos')
            latency = payload.get('replica_latency')
            replica_tp = payload.get('replica_tp')
            probation = payload.get('replica_probation')
            retry_budget = payload.get('retry_budget')
            journal_age = payload.get('journal_age_s')
            if isinstance(latency, dict):
                self.autoscaler.collect_latency_information(latency)
            with self._lb_lock:
                if isinstance(probation, list):
                    self._lb_probation = [str(u) for u in probation]
                if isinstance(retry_budget, (int, float)):
                    self._lb_retry_budget = float(retry_budget)
                self._lb_journal_age = (
                    float(journal_age)
                    if isinstance(journal_age, (int, float)) else None)
            if isinstance(inflight, dict) or isinstance(draining, list) \
                    or isinstance(affinity, dict) \
                    or isinstance(tenant_qos, dict) \
                    or isinstance(latency, dict) \
                    or isinstance(replica_tp, dict):
                with self._lb_lock:
                    if isinstance(inflight, dict):
                        self._lb_inflight = {
                            str(k): int(v) for k, v in inflight.items()
                            if isinstance(v, (int, float))}
                    if isinstance(draining, list):
                        self._lb_draining = {str(u) for u in draining}
                    if isinstance(affinity, dict):
                        self._lb_affinity = {
                            str(k): v for k, v in affinity.items()
                            if isinstance(v, dict)}
                    if isinstance(tenant_qos, dict):
                        self._lb_tenant_qos = dict(tenant_qos)
                    if isinstance(latency, dict):
                        self._lb_latency = {
                            str(k): v for k, v in latency.items()
                            if isinstance(v, dict)}
                    if isinstance(replica_tp, dict):
                        self._lb_tp = {
                            str(k): int(v)
                            for k, v in replica_tp.items()
                            if isinstance(v, (int, float))}
            return {
                'ready_replica_urls':
                    serve_state.ready_replica_endpoints(self.service_name)
            }
        if path == '/controller/state':
            return self.state_snapshot()
        if path == '/controller/update_service':
            spec = SkyTpuServiceSpec.from_json(payload['spec'])
            task_yaml = payload['task_yaml']
            self.update_mode = UpdateMode(payload.get('mode', 'rolling'))
            serve_state.set_service_spec(self.service_name, spec.to_json(),
                                         task_yaml)
            svc = serve_state.get_service(self.service_name)
            self.version = svc['version']
            self.spec = spec
            # Fleet to replace = current READY capacity (any version
            # older than the new one).  READY, not alive: a second
            # update issued mid-update must not count the half-built
            # previous-update fleet too — that would inflate the
            # replacement target to old+new combined.
            self._update_old_fleet = sum(
                1 for r in serve_state.get_replicas(self.service_name)
                if r['version'] < self.version and
                ReplicaStatus(r['status']) == ReplicaStatus.READY)
            # Re-make the autoscaler: the update may switch between fixed
            # and request-rate scaling.  Carry the QPS window over so an
            # in-place spec tweak does not forget the current load.
            new_autoscaler = autoscalers.Autoscaler.make(spec)
            if (isinstance(new_autoscaler,
                           autoscalers.RequestRateAutoscaler) and
                    isinstance(self.autoscaler,
                               autoscalers.RequestRateAutoscaler)):
                new_autoscaler.request_timestamps = list(
                    self.autoscaler.request_timestamps)
            if (isinstance(new_autoscaler,
                           autoscalers.SloLatencyAutoscaler) and
                    isinstance(self.autoscaler,
                               autoscalers.SloLatencyAutoscaler)):
                new_autoscaler.replica_latency = dict(
                    self.autoscaler.replica_latency)
            new_autoscaler.latest_version = self.version
            self.autoscaler = new_autoscaler
            self.replica_manager.update_version(spec, task_yaml,
                                                self.version)
            logger.info('[%s] updated to version %d', self.service_name,
                        self.version)
            return {'version': self.version}
        if path == '/controller/terminate_replica':
            rid = int(payload['replica_id'])
            self.replica_manager.scale_down(rid,
                                            purge=payload.get('purge', True))
            return {'terminated': rid}
        if path == '/v1/batches':
            b = self._ensure_batch()
            jid = b.submit(
                payload.get('prompts'),
                payload.get('max_new_tokens'),
                completion_window_s=float(
                    payload.get('completion_window_s', 3600.0)),
                tenant_id=payload.get('tenant_id'),
                temperature=payload.get('temperature'),
                job_id=payload.get('job_id'))
            return {'job_id': jid, 'status': b.status(jid)}  # wire-ok: client-facing API field
        if path.startswith('/v1/batches/'):
            return self.batch_status(path[len('/v1/batches/'):])
        raise KeyError(path)

    def _ensure_batch(self):
        """The coordinator, or a typed 503 while the plane is off."""
        if self.batch is None:
            from skypilot_tpu.serve.batch import BatchCoordinator
            path = constants.batch_journal_path()
            if not path:
                raise BatchPlaneDisabled(
                    'batch plane disabled: set SKYTPU_BATCH_JOURNAL '
                    'to a durable journal path')
            from skypilot_tpu.jobs import state as jobs_state
            self.batch = BatchCoordinator(
                path, self.lb_port,
                state_sink=jobs_state.record_batch_job)
        return self.batch

    def batch_status(self, job_id: str) -> dict:
        if self.batch is None:
            raise KeyError(job_id)
        return self.batch.status(job_id)  # wire-ok: client-facing API field

    def state_snapshot(self) -> dict:
        """Per-replica failure-counter block for observability: replica
        identity + probe failure count + the LB-reported load/drain
        view (matches the LB's /lb/stats on the other side)."""
        with self._lb_lock:
            lb_inflight = dict(self._lb_inflight)
            lb_draining = set(self._lb_draining)
            lb_affinity = dict(self._lb_affinity)
            lb_tenant_qos = dict(self._lb_tenant_qos)
            lb_latency = dict(self._lb_latency)
            lb_tp = dict(self._lb_tp)
            lb_probation = list(self._lb_probation)
            lb_retry_budget = self._lb_retry_budget
            lb_journal_age = self._lb_journal_age
        supervisor = self.lb_supervisor
        lb_block = {
            'probation_replicas': lb_probation,
            'retry_budget_remaining': lb_retry_budget,
            'journal_age_s': lb_journal_age,
            'supervisor': (None if supervisor is None
                           else supervisor.stats()),
        }
        replicas = []
        for r in serve_state.get_replicas(self.service_name):
            endpoint = r.get('endpoint')
            replicas.append({
                'replica_id': r['replica_id'],
                'status': r['status'],
                'version': r['version'],
                'is_spot': bool(r['is_spot']),
                'endpoint': endpoint,
                'consecutive_failures': r.get('consecutive_failures', 0),
                'failure_reason': r.get('failure_reason'),
                'inflight': lb_inflight.get(endpoint, 0),
                'draining': endpoint in lb_draining,
                'affinity': lb_affinity.get(endpoint),
                'latency': lb_latency.get(endpoint),
                # None until the LB's first probe of this replica
                # reports kv.tp (1 = DP, N = N-chip tensor parallel).
                'tp': lb_tp.get(endpoint),
            })
        return {'service': self.service_name, 'version': self.version,  # wire-ok: CLI/debug surface
                'replicas': replicas,
                'qos': lb_tenant_qos,
                'load_balancer': lb_block,
                'batch': (None if self.batch is None  # wire-ok: operator observability (batch backlog mirror)
                          else self.batch.backlog())}

    def _serve_http(self) -> None:
        controller = self

        class Handler(BaseHTTPRequestHandler):

            def log_message(self, *args):  # quiet
                pass

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get('Content-Length', 0))
                headers = {}
                try:
                    payload = json.loads(
                        self.rfile.read(length) or b'{}')
                    result = controller._handle(self.path, payload)
                    body = json.dumps(result).encode()
                    self.send_response(200)
                except KeyError:
                    body = b'{"error": "not found"}'
                    self.send_response(404)
                except ValueError as e:
                    # Bad batch submission (non-greedy, malformed
                    # prompts): the client's fault, typed as such.
                    body = json.dumps(
                        {'error': str(e),
                         'error_class': 'client'}).encode()
                    self.send_response(400)
                except BatchPlaneDisabled as e:
                    # Typed + retryable: the 5xx audit (ISSUE 20
                    # satellite) bans untyped 5xx without Retry-After.
                    body = json.dumps(
                        {'error': str(e), 'error_class': 'batch_disabled',
                         'retry_after_s': 5.0}).encode()
                    headers['Retry-After'] = '5'
                    self.send_response(503)
                except Exception as e:  # pylint: disable=broad-except
                    body = json.dumps(
                        {'error': str(e),
                         'error_class': 'internal'}).encode()
                    self.send_response(500)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split('?', 1)[0]
                if path == '/controller/state':
                    body = json.dumps(controller.state_snapshot()).encode()
                    self.send_response(200)
                elif path.startswith('/v1/batches/'):
                    try:
                        body = json.dumps(controller.batch_status(
                            path[len('/v1/batches/'):])).encode()
                        self.send_response(200)
                    except KeyError:
                        body = b'{"error": "not found"}'
                        self.send_response(404)
                else:
                    body = b'{"error": "not found"}'
                    self.send_response(404)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(('0.0.0.0', self.port), Handler)
        self._httpd.daemon_threads = True
        self._httpd.serve_forever(poll_interval=0.2)

    # ------------------------------------------------------- control loop

    def run_once(self) -> None:
        """One control tick: probe, reconcile clusters, autoscale."""
        now = time.time()  # det-ok: probe pacing; tests drive run_once()
        if now - self._last_probe >= constants.probe_interval():
            self._last_probe = now
            self.replica_manager.probe_all()
        if (now - self._last_cluster_check >=
                constants.job_status_interval()):
            self._last_cluster_check = now
            self.replica_manager.check_replica_clusters()

        with self._lb_lock:
            lb_inflight = dict(self._lb_inflight)
            lb_draining = set(self._lb_draining)
        replicas = [
            autoscalers.ReplicaView(
                replica_id=r['replica_id'],
                status=ReplicaStatus(r['status']),
                version=r['version'],
                is_spot=bool(r['is_spot']),
                draining=r.get('endpoint') in lb_draining,
                inflight=lb_inflight.get(r.get('endpoint'), 0),
            ) for r in serve_state.get_replicas(self.service_name)
        ]
        if self.batch is not None and hasattr(
                self.autoscaler, 'collect_batch_backlog'):
            # Batch backlog feeds the SLO autoscaler: scale up to meet
            # the completion window, release the surplus when it drains.
            self.autoscaler.collect_batch_backlog(self.batch.backlog())
        update_in_progress = any(
            r.version < self.version and r.alive for r in replicas)
        if not update_in_progress:
            for decision in self.autoscaler.evaluate_scaling(replicas):
                if decision.operator == DecisionOperator.SCALE_UP:
                    self.replica_manager.scale_up(
                        use_spot=decision.target.get('use_spot', False))
                else:
                    self.replica_manager.scale_down(
                        decision.target['replica_id'])
        else:
            # While an update is replacing the fleet, _update_replicas
            # owns sizing: the autoscaler's surplus drain (old+new alive
            # > its target, _scale_down_order preferring OLD versions)
            # would otherwise tear down old READY replicas the pacing
            # below is deliberately keeping alive.  Demand changes defer
            # until the update completes.
            self._update_replicas(replicas)
        self._refresh_service_status(replicas)

    def _update_replicas(
            self, replicas: List[autoscalers.ReplicaView]) -> None:
        """Replace old-version replicas per the active UpdateMode
        (parity: sky/serve/core.py:309 rolling|blue_green consumed by
        replica_managers.py:1176)."""
        old = [r for r in replicas if r.version < self.version and r.alive]
        if not old:
            return
        latest_ready = sum(
            1 for r in replicas if r.version >= self.version and
            r.status == ReplicaStatus.READY)
        latest_alive = sum(
            1 for r in replicas if r.version >= self.version and r.alive)
        # Replace the LIVE fleet, not min_replicas: an autoscaled service
        # holding 5 replicas under load gets 5 replacements, and drains
        # pace against that size (self._update_old_fleet is recorded at
        # update time; 0 = controller restarted mid-update, degrade to
        # min_replicas).
        target = max(self.spec.min_replicas, self._update_old_fleet)
        if self.update_mode is UpdateMode.BLUE_GREEN:
            # Bring the full green fleet up first; blue drains only once
            # green is fully READY (no capacity dip, 2x resources).
            if latest_alive < target:
                for _ in range(target - latest_alive):
                    self.replica_manager.scale_up()
            if latest_ready >= target:
                for r in old:
                    self.replica_manager.scale_down(r.replica_id)
            return
        # Rolling: surge of ONE — launch a single new replica at a time
        # (next one only once it is READY) — with CUMULATIVE drain
        # pacing: each new READY replica grants exactly one old-drain
        # permit, and permits already spent (old fleet shrinkage) are
        # subtracted, so ready capacity never collapses toward
        # min_replicas faster than replacements arrive.
        if latest_alive < target and latest_alive == latest_ready:
            self.replica_manager.scale_up()
        old_ready = [r for r in old if r.status == ReplicaStatus.READY]
        old_not_ready = [r for r in old
                         if r.status != ReplicaStatus.READY]
        # Not-yet-ready old replicas add no capacity; drain them once a
        # replacement is in flight.  (Conservative: they consume drain
        # permits via the fleet-shrinkage accounting below.)
        if latest_alive > 0:
            for r in old_not_ready:
                self.replica_manager.scale_down(r.replica_id)
        old_drained = max(0, self._update_old_fleet - len(old))
        permits = latest_ready - old_drained
        for r in old_ready[:max(0, min(permits, len(old_ready)))]:
            self.replica_manager.scale_down(r.replica_id)

    def _refresh_service_status(
            self, replicas: List[autoscalers.ReplicaView]) -> None:
        svc = serve_state.get_service(self.service_name)
        if svc is None or svc['status'] == (
                ServiceStatus.SHUTTING_DOWN.value):
            return
        status = ServiceStatus.from_replica_statuses(
            [r.status for r in replicas])
        # FAILED only counts while we cannot serve at all; a failed record
        # next to READY replicas is degraded-but-serving.
        if (status == ServiceStatus.FAILED and
                any(not r.status.is_failed() for r in replicas)):
            status = ServiceStatus.REPLICA_INIT
        if svc['status'] != status.value:
            serve_state.set_service_status(self.service_name, status)

    def run(self) -> None:
        http_thread = threading.Thread(target=self._serve_http,
                                       daemon=True,
                                       name=f'http-{self.service_name}')
        http_thread.start()
        logger.info('[%s] controller listening on :%d', self.service_name,
                    self.port)
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception as e:  # pylint: disable=broad-except
                logger.error('[%s] control tick error: %s',
                             self.service_name, e, exc_info=True)
            self._stop.wait(constants.autoscaler_interval())

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
