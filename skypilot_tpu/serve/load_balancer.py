"""HTTP load balancer: streams user traffic to ready replicas.

Parity: sky/serve/load_balancer.py:22-229 (FastAPI/httpx reverse proxy
with controller sync + retry across replicas).  Built on stdlib
ThreadingHTTPServer + http.client so replica responses stream through in
chunks (LLM serving needs streaming) without extra dependencies.

Replica fault tolerance (the supervisor half of the proxy):

- **Active health probing + circuit breaking.**  A probe thread GETs
  every known replica's ``/healthz`` on a short interval
  (`constants.lb_health_probe_interval`).  Connection-level failures
  trip a per-replica closed→open→half-open breaker (exponential
  backoff + jitter, `circuit_breaker.CircuitBreaker`), ejecting dead
  replicas from routing in probe-time instead of controller-sync-time;
  a later successful probe closes the breaker, re-admitting the
  replica just as fast.  Any HTTP response proves a live process —
  only refused/reset/timeout (or an explicit ``status: dead`` healthz
  document) count against the breaker, so plain HTTP replicas without
  /healthz keep working.
- **Drain honoring.**  A replica advertising ``draining`` (via
  /healthz, or a 503 + ``X-SkyTpu-Draining`` answer) stops receiving
  new requests while its in-flight work finishes.
- **Deterministic mid-stream failover.**  A ``/generate`` SSE stream
  whose replica dies mid-decode is RESUMED on a survivor: the LB
  reconstructs a continuation request from the prompt plus the tokens
  already relayed and stitches the survivor's events into the same
  client stream (greedy decoding makes the replay byte-identical).
  Sampled (temperature>0) or unbounded (no max_new_tokens) streams are
  non-resumable: once tokens have been relayed, a replica death fails
  them FAST with a typed error event instead of a silent truncation.
- **Deadline budget.**  A request's ``deadline_s`` bounds the replica
  connection timeout (instead of the blanket 120 s) and decrements
  across failover attempts, so replay can never exceed the client's
  original deadline.

Control-plane resilience (PR 18):

- **Warm restart.**  With a journal (`serve/lb_journal.py`) attached,
  the LB persists its slow-moving state — breaker machines + backoff
  clocks (fsync'd on transitions), the affinity ``_seen`` residency
  map, per-replica latency/tp snapshots, tenant bucket levels, the
  retry-budget level — and a restarted LB re-adopts it instead of
  starting blind.  Adopted replicas are *unverified* until one probe
  round confirms them: the journal is trusted for backoff clocks
  (pessimistic state ages out safely) but never for liveness.
- **Gray-failure probation.**  Per-replica TTFT EWMAs are compared to
  the fleet median after every probe round; a sustained outlier
  (`circuit_breaker.evaluate_probation`) is shed to
  ``lb_probation_weight`` of its traffic while probes keep watching —
  a fail-slow replica stops dragging fleet p99 without a full eject.
- **Retry budgets.**  Failure-driven retries/failovers withdraw from a
  Finagle-style token budget refilled by successes; a dry budget turns
  the next retry into a typed 503 ``error_class='retry_budget'``
  instead of amplifying a brownout into a retry storm.
- **TTFT hedging** (``SKYTPU_LB_HEDGE_MS``).  A resumable greedy
  stream whose first byte misses the hedge deadline is issued to a
  second replica; whichever arm produces the first event is promoted
  to the client stream and the loser is cancelled (single-promotion
  guard = dedup; ``hedges``/``hedge_wins``/``hedge_cancelled`` count
  the wasted work).

``GET /lb/stats`` exports the counters (attempts, failovers, breaker
opens, drains honored, streams resumed, hedges, retry-budget level).
"""
import collections
import json
import math
import os
import socket
import threading
import time
import urllib.parse
import urllib.request
import zlib
from http.client import HTTPConnection, HTTPException
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

import numpy as np

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu import logsys
from skypilot_tpu.serve import constants
from skypilot_tpu.serve import qos as serve_qos
from skypilot_tpu.serve.circuit_breaker import CircuitBreaker
from skypilot_tpu.serve.lb_journal import LBJournal
from skypilot_tpu.serve.load_balancing_policies import (LoadBalancingPolicy,
                                                        RequestContext)

logger = logsys.init_logger(__name__)

_HOP_BY_HOP = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding', 'upgrade'
}
_MAX_ATTEMPTS = 3
_DEFAULT_REPLICA_TIMEOUT = 120.0
_PROBE_TIMEOUT = 2.0


class _ClientGone(Exception):
    """The downstream client hung up; abandon the whole request."""


class _HedgeCancelled(Exception):
    """This hedge arm lost the race; stop relaying and unwind."""


class _ReplicaHealth:
    """LB-side view of one replica: breaker + drain flag + load."""

    __slots__ = ('breaker', 'draining', 'outstanding')

    def __init__(self, breaker: CircuitBreaker):
        self.breaker = breaker
        self.draining = False
        self.outstanding = 0


class _SSERelay:
    """One client-facing SSE stream, possibly stitched across replicas.

    Forwards complete `data: ...\\n\\n` events RAW (a stream that never
    fails over is byte-identical to talking to the replica directly);
    tracks the token ids relayed so a failover can reconstruct the
    continuation, and rewrites only the final done event — and only
    after an actual failover — so the client sees one uninterrupted
    stream whose `output_tokens` covers the whole generation.
    """

    def __init__(self, handler: BaseHTTPRequestHandler):
        self.handler = handler
        self.headers_sent = False
        self.streamed: List[int] = []   # token ids relayed to the client
        self.chunks_forwarded = 0
        self.resumed = False            # a continuation attempt ran

    def send_headers(self, resp) -> None:
        self.send_headers_raw(resp.status, resp.reason, resp.getheaders())

    def send_headers_raw(self, status: int, reason: str, headers) -> None:
        if self.headers_sent:
            return
        h = self.handler
        h.send_response(status, reason)
        for k, v in headers:
            if k.lower() not in _HOP_BY_HOP and \
                    k.lower() != 'content-length':
                h.send_header(k, v)
        # SSE is close-delimited through the proxy.
        h.send_header('Connection', 'close')
        h.close_connection = True
        h.end_headers()
        self.headers_sent = True

    def send_buffered_response(self, status: int, reason: str,
                               headers, data: bytes) -> None:
        """One fully-buffered non-SSE response (a replica's non-200
        answer before any stream started)."""
        try:
            h = self.handler
            h.send_response(status, reason)
            for k, v in headers:
                if k.lower() not in _HOP_BY_HOP and \
                        k.lower() != 'content-length':
                    h.send_header(k, v)
            h.send_header('Content-Length', str(len(data)))
            h.end_headers()
            h.wfile.write(data)
        except (OSError, socket.timeout) as e:
            raise _ClientGone() from e
        self.headers_sent = True

    def forward(self, raw: bytes) -> None:
        try:
            self.handler.wfile.write(raw)
            self.handler.wfile.flush()
        except (OSError, socket.timeout) as e:
            raise _ClientGone() from e

    def note_tokens(self, tokens) -> None:
        """Record token ids relayed to the client (continuation
        reconstruction input)."""
        self.streamed.extend(int(t) for t in tokens)

    def emit_event(self, payload: dict) -> None:
        self.forward(b'data: ' + json.dumps(payload).encode() + b'\n\n')

    def emit_error_event(self, message: str, error_class: str) -> None:
        """Typed terminal event for a stream the LB cannot resume."""
        try:
            self.emit_event({
                'done': True,
                'error': message,
                'error_class': error_class,
                'finish_reason': 'error',
                'output_tokens': list(self.streamed),
                'ttft_s': 0.0, 'latency_s': 0.0,
            })
        except _ClientGone:
            pass


class _BufferRelay:
    """One hedge arm's view of the client stream: buffers everything
    until the arm is PROMOTED (buffer replays into the real relay and
    later writes stream straight through) or CANCELLED (writes raise
    `_HedgeCancelled` and the arm unwinds).  The promote/cancel edge is
    taken exactly once under `_lock` — that single-promotion guard is
    what dedups the hedged request: the client can never observe bytes
    from both arms.
    """

    def __init__(self, inner: _SSERelay,
                 on_first: Callable[[], None]) -> None:
        self.inner = inner
        self._on_first = on_first
        self.headers_sent = False
        self.streamed: List[int] = list(inner.streamed)
        self._base = len(inner.streamed)
        self.chunks_forwarded = inner.chunks_forwarded
        self.resumed = inner.resumed
        self.first_event = threading.Event()
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.load_balancer._hedge_relay_lock')
        self._buf: list = []       # guarded-by: _lock
        self._state = 'buffering'  # guarded-by: _lock

    def send_headers(self, resp) -> None:
        self.send_headers_raw(resp.status, resp.reason, resp.getheaders())

    def send_headers_raw(self, status: int, reason: str, headers) -> None:
        with self._lock:
            if self._state == 'cancelled':
                raise _HedgeCancelled()
            if self._state == 'promoted':
                self.inner.send_headers_raw(status, reason, list(headers))
            elif not self.headers_sent:
                self._buf.append(
                    ('headers', (status, reason,
                                 [list(kv) for kv in headers])))
            self.headers_sent = True

    def send_buffered_response(self, status: int, reason: str,
                               headers, data: bytes) -> None:
        with self._lock:
            if self._state == 'cancelled':
                raise _HedgeCancelled()
            if self._state == 'promoted':
                self.inner.send_buffered_response(
                    status, reason, list(headers), data)
            else:
                self._buf.append(
                    ('response', (status, reason,
                                  [list(kv) for kv in headers],
                                  bytes(data))))
            self.headers_sent = True
        self.first_event.set()
        self._on_first()

    def forward(self, raw: bytes) -> None:
        with self._lock:
            if self._state == 'cancelled':
                raise _HedgeCancelled()
            if self._state == 'promoted':
                self.inner.forward(raw)
            else:
                self._buf.append(('raw', bytes(raw)))
        self.first_event.set()
        self._on_first()

    def emit_event(self, payload: dict) -> None:
        self.forward(b'data: ' + json.dumps(payload).encode() + b'\n\n')

    def note_tokens(self, tokens) -> None:
        # Under the hedge lock: promote() merges + aliases `streamed`
        # while holding it, so an append lands either in the buffer's
        # list (pre-merge, carried over) or the inner relay's (post).
        with self._lock:
            self.streamed.extend(int(t) for t in tokens)

    def promote(self) -> None:
        """This arm won: replay the buffer into the client stream; all
        later writes go straight through.  Idempotent; a cancelled arm
        stays cancelled."""
        with self._lock:
            if self._state != 'buffering':
                return
            self._state = 'promoted'
            # Merge token bookkeeping FIRST, then alias: the streaming
            # thread appends to whatever `self.streamed` points at, so
            # after the alias its appends land in the inner relay.
            self.inner.streamed.extend(self.streamed[self._base:])
            self.streamed = self.inner.streamed
            buffered, self._buf = self._buf, []
            for kind, args in buffered:
                if kind == 'headers':
                    self.inner.send_headers_raw(*args)
                elif kind == 'response':
                    self.inner.send_buffered_response(*args)
                else:
                    self.inner.forward(args)

    def cancel(self) -> None:
        """This arm lost: drop the buffer; the arm's next write raises
        and its attempt unwinds as outcome 'cancelled'."""
        with self._lock:
            if self._state == 'buffering':
                self._state = 'cancelled'
                self._buf = []


class SkyTpuLoadBalancer:

    def __init__(self, controller_url: Optional[str], port: int,
                 policy: LoadBalancingPolicy,
                 clock: Callable[[], float] = time.monotonic,
                 journal: Optional[LBJournal] = None,
                 server_cls: type = ThreadingHTTPServer):
        """controller_url=None: standalone mode (tests, the chaos
        harness) — no controller sync; the caller seeds the policy's
        replica set directly.  ``clock``: monotonic-seconds source for
        the per-request deadline budget (injectable so failover-budget
        tests replay deterministically).  ``journal``: warm-restart
        journal to adopt + keep current (None = journalling off, the
        pre-existing cold-restart behaviour).  ``server_cls``: the
        HTTP server base class run() builds on — the chaos harness
        injects a socket-tracking subclass so `lb_kill` can sever live
        client connections like a real process death."""
        self.controller_url = controller_url
        self._clock = clock
        self.port = port
        self.policy = policy
        self._request_timestamps: List[float] = []  # guarded-by: _ts_lock
        self._ts_lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.load_balancer._ts_lock')
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # Per-replica health: breaker + draining + outstanding count.
        self._health_lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.load_balancer._health_lock')
        self._health: Dict[str, _ReplicaHealth] = {}  # guarded-by: _health_lock
        # Tensor degree each replica advertises through /healthz kv.tp
        # (1 = unsharded/DP): synced to the controller so the fleet
        # snapshot shows which replicas are tensor-parallel.
        self._replica_tp: Dict[str, int] = {}  # guarded-by: _health_lock
        # Host-RAM KV tier section each replica advertises through
        # /healthz kv.host_tier: aggregated into /lb/stats and shown
        # to the autoscaler/operator as fleet spill/restore pressure.
        self._replica_host_tier: Dict[str, dict] = {}  # guarded-by: _health_lock
        self._stats_lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.load_balancer._stats_lock')
        self._counters = {  # guarded-by: _stats_lock
            'requests': 0,
            'attempts': 0,
            'failovers': 0,
            'streams_resumed': 0,
            'drains_honored': 0,
            'non_resumable_failures': 0,
            'deadline_exhausted': 0,
            'probe_failures': 0,
            'rate_limited': 0,
            # Drain-time hot-set handoff (warm failover): transfers
            # attempted, prefixes adopted by survivors, failures.
            'hot_handoffs': 0,
            'handoff_prefixes': 0,
            'handoff_failures': 0,
            # TTFT hedging: hedges launched, races the hedge arm won,
            # loser arms cancelled (wasted replica work), and retries
            # refused because the retry budget ran dry.
            'hedges': 0,
            'hedge_wins': 0,
            'hedge_cancelled': 0,
            'retry_budget_exhausted': 0,
            # Batch plane (ISSUE 20): batch-class rows routed, and row
            # leases re-adopted (and released) from the journal after
            # a warm restart — orphaned leases mean the old LB died
            # with rows in flight; the coordinator's retry re-runs
            # them, so adoption only has to account, not replay.
            'batch_rows': 0,
            'batch_leases_adopted': 0,
        }
        # Live batch-row leases (journal-backed when a journal is
        # configured): request_id -> 1 while the row relays.
        self._batch_leases: set = set()  # guarded-by: _stats_lock
        # LB-side QoS plane: per-tenant token buckets (serve/qos.py)
        # share the LB's injected clock so rate-limit tests replay
        # deterministically.
        self.limiter = serve_qos.TenantRateLimiter(clock=self._clock)
        # Per-replica TTFT samples (seconds), bounded rolling windows.
        # Streamed generates record time-to-first-event; buffered
        # generates record whole-response latency (an upper bound on
        # TTFT — still SLO-relevant signal).  Summaries feed /lb/stats
        # and the controller sync for the SLO autoscaler.
        self._latency: Dict[str, collections.deque] = {}  # guarded-by: _stats_lock
        # Fleet-wide retry budget: failure-driven retries/hedges spend,
        # completed requests earn (serve/qos.RetryBudget).
        self.retry_budget = serve_qos.RetryBudget(
            ratio=constants.lb_retry_budget_ratio(),
            reserve_per_s=constants.lb_retry_budget_reserve(),
            cap=constants.lb_retry_budget_cap(),
            clock=self._clock)
        # Gray-failure probation knobs (read once; circuit_breaker.py
        # holds the per-replica state machines).
        self._probation_weight = constants.lb_probation_weight()
        self._probation_k = constants.lb_probation_k()
        self._probation_enter = constants.lb_probation_enter()
        self._probation_exit = constants.lb_probation_exit()
        self._ewma_alpha = constants.lb_ewma_alpha()
        self._hedge_s = max(0.0, constants.lb_hedge_ms() / 1000.0)
        # Probation traffic shed draws: seeded from the port so a fleet
        # replays its shed pattern run-over-run.
        self._shed_rng = np.random.default_rng(port)  # guarded-by: _health_lock
        self._server_cls = server_cls
        # Warm-restart journal.  Replicas adopted FROM the journal are
        # quarantined in _adopted_unverified until one probe round
        # confirms them (journalled backoffs are trusted; journalled
        # liveness never is).
        self.journal = journal
        self._adopted_unverified: set = set()  # guarded-by: _health_lock
        self._breaker_snapshots: Dict[str, dict] = {}  # guarded-by: _health_lock
        if journal is not None:
            self._adopt_journal()

    # ----------------------------------------------------- health/breakers

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def _record_ttft(self, replica: str, seconds: float) -> None:
        with self._stats_lock:
            window = self._latency.get(replica)
            if window is None:
                window = collections.deque(
                    maxlen=constants.slo_latency_window())
                self._latency[replica] = window
            window.append(seconds)
        # Gray-failure track: the breaker's TTFT EWMA is what
        # _evaluate_probation compares against the fleet median.
        self._rep(replica).breaker.record_latency(seconds)

    def _latency_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-replica TTFT percentiles (ms) over the rolling window —
        the SLO autoscaler's target-tracking input."""
        with self._stats_lock:
            samples = {u: list(w) for u, w in self._latency.items() if w}
        out: Dict[str, Dict[str, float]] = {}
        for url, vals in samples.items():
            vals.sort()
            out[url] = {
                'ttft_p50_ms': 1000.0 * vals[len(vals) // 2],
                'ttft_p95_ms': 1000.0 * vals[
                    min(len(vals) - 1, int(math.ceil(0.95 * len(vals))) - 1)],
                'count': len(vals),
            }
        return out

    def _rep(self, url: str) -> _ReplicaHealth:
        with self._health_lock:
            h = self._health.get(url)
            if h is None:
                # Seed the jitter stream from the URL so a given fleet
                # lays out backoff deterministically run-over-run.
                breaker = CircuitBreaker(
                    rng=np.random.default_rng(
                        zlib.crc32(url.encode()) & 0xffffffff),
                    probation_k=self._probation_k,
                    probation_enter=self._probation_enter,
                    probation_exit=self._probation_exit,
                    ewma_alpha=self._ewma_alpha)
                snap = self._breaker_snapshots.pop(url, None)
                if snap is not None:
                    breaker.restore(snap)
                if self.journal is not None:
                    # fsync'd journal write on every breaker edge: an
                    # OPEN state that doesn't survive a crash means one
                    # guaranteed-bad request after restart.
                    breaker.on_transition = (
                        lambda _state, u=url: self._journal_breaker(u))
                h = _ReplicaHealth(breaker)
                self._health[url] = h
            return h

    # -------------------------------------------- warm-restart journal

    _JOURNAL_BREAKER_PREFIX = 'breaker:'

    def _adopt_journal(self) -> None:
        """Re-adopt the journal's state at construction.  Breaker
        snapshots are staged for lazy _rep() materialisation; every
        journalled replica starts UNVERIFIED (excluded from routing)
        until one probe round answers for it."""
        snap = self.journal.snapshot()
        urls = set()
        with self._health_lock:
            for key, doc in snap.items():
                if key.startswith(self._JOURNAL_BREAKER_PREFIX) and \
                        isinstance(doc, dict):
                    urls.add(key[len(self._JOURNAL_BREAKER_PREFIX):])
                    self._breaker_snapshots[
                        key[len(self._JOURNAL_BREAKER_PREFIX):]] = doc
        seen = snap.get('affinity_seen')
        if isinstance(seen, dict):
            self.policy.import_seen(seen)
        qos_doc = snap.get('qos')
        if isinstance(qos_doc, dict):
            self.limiter.restore(qos_doc)
        budget = snap.get('retry_budget')
        if isinstance(budget, dict):
            self.retry_budget.restore(budget)
        latency = snap.get('latency')
        if isinstance(latency, dict):
            with self._stats_lock:
                for url, vals in latency.items():
                    if isinstance(vals, list):
                        window = collections.deque(
                            maxlen=constants.slo_latency_window())
                        window.extend(float(v) for v in vals)
                        self._latency[url] = window
                        urls.add(url)
        tp = snap.get('replica_tp')
        if isinstance(tp, dict):
            with self._health_lock:
                for url, v in tp.items():
                    self._replica_tp[url] = int(v)
                    urls.add(url)
        # Batch-row leases the dead LB held at the crash: account for
        # them (the coordinator's retry re-runs the rows; exactly-once
        # comes from the row hash dedup, not from the lease), then
        # release so compaction clears the keys.
        orphaned = [k for k, doc in snap.items()
                    if k.startswith(self._JOURNAL_LEASE_PREFIX) and
                    isinstance(doc, dict) and doc.get('held')]
        if orphaned:
            self._bump('batch_leases_adopted', len(orphaned))
            for key in orphaned:
                self.journal.put(key, {'held': False})
        with self._health_lock:
            self._adopted_unverified |= urls
        for url in sorted(urls):
            self._rep(url)   # materialise now: restores the snapshot
        if urls:
            logger.info(
                'LB: adopted journal state for %d replica(s); '
                'awaiting one probe round before routing to them',
                len(urls))

    def _journal_breaker(self, url: str) -> None:
        """Persist one breaker's snapshot, fsync'd (breaker edges are
        the rare, high-value journal writes)."""
        if self.journal is None:
            return
        with self._health_lock:
            h = self._health.get(url)
        if h is not None:
            self.journal.put(self._JOURNAL_BREAKER_PREFIX + url,
                             h.breaker.snapshot(), fsync=True)

    def _journal_soft_state(self) -> None:
        """Persist the slow-moving soft state once per probe round —
        flushed, not fsync'd: losing a probe-interval of it is free."""
        if self.journal is None:
            return
        seen = self.policy.export_seen()
        if seen is not None:
            self.journal.put('affinity_seen', seen)
        self.journal.put('qos', self.limiter.snapshot())
        self.journal.put('retry_budget', self.retry_budget.snapshot())
        with self._stats_lock:
            latency = {u: list(w) for u, w in self._latency.items() if w}
        with self._health_lock:
            tp = dict(self._replica_tp)
        self.journal.put('latency', latency)
        self.journal.put('replica_tp', tp)

    def _mark_verified(self, url: str) -> None:
        with self._health_lock:
            self._adopted_unverified.discard(url)

    # --------------------------------------------- gray-failure probation

    def _evaluate_probation(self) -> None:
        """Once per probe round: compare every replica's TTFT EWMA to
        the fleet median and step the probation state machines.  Needs
        two replicas with samples — with one signal there is no
        'fleet' to be an outlier of."""
        with self._health_lock:
            breakers = {u: h.breaker for u, h in self._health.items()}
        ewmas = [b.latency_ewma for b in breakers.values()]
        ewmas = [e for e in ewmas if e is not None]
        if len(ewmas) < 2:
            return
        median = float(np.median(np.asarray(ewmas)))
        for url, breaker in sorted(breakers.items()):
            if breaker.evaluate_probation(median):
                # (on_transition already journalled the edge, fsync'd.)
                verb = ('entered' if breaker.in_probation() else 'left')
                logger.warning(
                    'LB: replica %s %s probation (TTFT EWMA %s s vs '
                    'fleet median %.4f s)', url, verb,
                    breaker.latency_ewma, median)

    def reset_gray_state(self) -> int:
        """Forget every replica's gray-failure evidence (TTFT EWMAs,
        hysteresis streaks, probation flags) and the per-replica
        latency windows behind ``lb_stats()['replica_latency']``.
        Probation normally clears through fresh healthy samples, but a
        replica shed to the probation weight may see too little
        traffic to ever refresh its stale EWMA — after a maintenance
        window (or between fault-injection episodes that must not
        contaminate each other) the operator knows the old evidence is
        dead.  Returns how many replicas left probation."""
        with self._health_lock:
            breakers = [h.breaker for h in self._health.values()]
        exited = sum(1 for b in breakers if b.reset_latency_state())
        with self._stats_lock:
            self._latency.clear()
        return exited

    @staticmethod
    def _hot_handoff_enabled() -> bool:
        return os.environ.get('SKYTPU_LB_HOT_HANDOFF', '1'
                              ).strip().lower() not in ('0', 'false',
                                                        'no', 'off')

    def _mark_draining(self, url: str, draining: bool) -> None:
        h = self._rep(url)
        with self._health_lock:
            fresh = draining and not h.draining
            if fresh:
                self._bump('drains_honored')
            h.draining = draining
        if fresh and self._hot_handoff_enabled():
            # Warm failover: while the drain finishes its in-flight
            # work, ship the replica's hottest radix prefixes to the
            # survivors the affinity ring routes them to.  Off-thread:
            # _mark_draining runs on probe/proxy paths that must not
            # block on device→host gathers.
            threading.Thread(target=self._handoff_hot_set, args=(url,),
                             daemon=True, name='lb-hot-handoff').start()

    def _adjust_outstanding(self, url: str, delta: int) -> None:
        h = self._rep(url)
        with self._health_lock:
            h.outstanding = max(0, h.outstanding + delta)

    def _routing_exclude(self, tried) -> set:
        """Replicas a select must skip: already tried this request,
        breaker open, draining, journal-adopted-but-unverified, or (in
        ~1-probation_weight of draws) in probation.  The quarantine and
        the shed are both availability-bounded: they never empty the
        candidate set — a fleet that is entirely unverified or entirely
        in probation still serves."""
        ex = set(tried)
        ready = set(self.policy.ready_replicas)
        with self._health_lock:
            probation = []
            for url, h in self._health.items():
                if h.draining or not h.breaker.available():
                    ex.add(url)
                elif h.breaker.in_probation():
                    probation.append(url)
            unverified = {u for u in self._adopted_unverified
                          if u in ready}
            if unverified and (ready - ex - unverified):
                ex |= unverified
            for url in sorted(probation):
                if float(self._shed_rng.random()) < \
                        self._probation_weight:
                    continue   # the trickle that keeps it convalescing
                if ready - ex - {url}:
                    ex.add(url)
        return ex

    def _probe_replica_once(self, url: str) -> None:
        h = self._rep(url)
        parsed = urllib.parse.urlsplit(url)
        conn = HTTPConnection(parsed.hostname, parsed.port,
                              timeout=_PROBE_TIMEOUT)
        try:
            conn.request('GET', '/healthz',
                         headers={'Host': parsed.netloc,
                                  'Connection': 'close'})
            resp = conn.getresponse()
            body = resp.read()
            status = resp.status
        except (OSError, socket.timeout, HTTPException):
            h.breaker.record_failure()
            self._bump('probe_failures')
            return
        finally:
            conn.close()
        doc = None
        try:
            doc = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            pass
        if not isinstance(doc, dict) or 'status' not in doc:
            # Not a /healthz speaker (404 from a plain HTTP replica):
            # any response proves the process is alive.
            h.breaker.record_success()
            self._mark_verified(url)
            self._mark_draining(url, False)
            return
        # Affinity-aware policies read kv/radix counters out of the
        # healthz document (hit rate raises the load bound, near-full
        # occupancy penalizes the replica).
        self.policy.observe_replica(url, doc)
        kv = doc.get('kv')
        if isinstance(kv, dict):
            # kv.tp: the engine's tensor degree — a TP replica owns
            # 1/tp of the KV heads per chip; recorded so the
            # controller's fleet snapshot distinguishes TP from DP
            # replicas behind one LB.
            with self._health_lock:
                self._replica_tp[url] = int(kv.get('tp') or 1)
            ht = kv.get('host_tier')
            if isinstance(ht, dict):
                with self._health_lock:
                    self._replica_host_tier[url] = dict(ht)
        state = doc.get('status')
        self._mark_draining(url, bool(doc.get('draining')) or
                            state == 'draining')
        if status == 200 or state in ('ok', 'draining'):
            # 'draining' is alive (it is finishing real work) — the
            # drain flag, not the breaker, keeps traffic away.
            h.breaker.record_success()
            self._mark_verified(url)
        else:
            # Explicit 'dead' (serving loop gave up) or 'starting':
            # a live process that cannot serve is ejected like a dead
            # one, recovering through the same half-open path.
            h.breaker.record_failure()
            self._bump('probe_failures')

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            for url in list(self.policy.ready_replicas):
                if self._stop.is_set():
                    return
                self._probe_replica_once(url)
            self._evaluate_probation()
            self._journal_soft_state()
            self._stop.wait(constants.lb_health_probe_interval())

    # ------------------------------------------------- hot-set handoff

    def _replica_json(self, url: str, path: str,
                      body: Optional[dict] = None,
                      timeout: float = 30.0) -> Optional[dict]:
        """GET (body=None) or POST one JSON document to a replica;
        None on any failure (connection, non-200, non-JSON)."""
        parsed = urllib.parse.urlsplit(url)
        conn = HTTPConnection(parsed.hostname, parsed.port,
                              timeout=timeout)
        try:
            if body is None:
                conn.request('GET', path,
                             headers={'Host': parsed.netloc,
                                      'Connection': 'close'})
            else:
                conn.request('POST', path,
                             body=json.dumps(body).encode(),
                             headers=self._replica_headers(url))
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            doc = json.loads(data)
            return doc if isinstance(doc, dict) else None
        except (OSError, socket.timeout, HTTPException, ValueError,
                UnicodeDecodeError):
            return None
        finally:
            conn.close()

    def _handoff_survivor(self, context: RequestContext,
                          src: str) -> Optional[str]:
        """Destination for one hot prefix: the affinity ring's owner
        when it is alive and not draining (the replica its future
        traffic routes to anyway), else the first usable survivor."""
        owner = None
        owner_fn = getattr(self.policy, 'owner_of', None)
        if callable(owner_fn):
            try:
                owner = owner_fn(context)
            except Exception:  # pylint: disable=broad-except
                owner = None
        with self._health_lock:
            bad = {u for u, h in self._health.items()
                   if h.draining or not h.breaker.available()}
        bad.add(src)
        if owner is not None and owner not in bad:
            return owner
        for u in self.policy.ready_replicas:
            if u not in bad:
                return u
        return None

    def _handoff_hot_set(self, src: str) -> None:
        """Drain-time warm failover: pull the draining replica's hot
        radix prefixes (GET /hot_prefixes) and ship each to the
        survivor the affinity ring owns it to (POST /adopt_blocks),
        so the next matching prompt prefills suffix-only instead of
        from scratch (~full re-prefill of added p99 saved)."""
        payload = self._replica_json(src, '/hot_prefixes')
        if payload is None:
            self._bump('handoff_failures')
            return
        prefixes = payload.get('prefixes')
        if not isinstance(prefixes, list) or not prefixes:
            return               # nothing hot to ship — not a failure
        groups: Dict[str, List[dict]] = {}
        for p in prefixes:
            if not isinstance(p, dict) or \
                    not isinstance(p.get('tokens'), list):
                continue
            adapter = p.get('adapter')
            ctx = RequestContext(
                tokens=[int(t) for t in p['tokens']],
                adapter=adapter if isinstance(adapter, str) else None)
            dst = self._handoff_survivor(ctx, src)
            if dst is not None:
                groups.setdefault(dst, []).append(p)
        if not groups:
            self._bump('handoff_failures')
            return
        header = {k: v for k, v in payload.items() if k != 'prefixes'}
        shipped = 0
        failed = False
        for dst, batch in sorted(groups.items()):
            doc = dict(header)
            doc['prefixes'] = batch
            res = self._replica_json(dst, '/adopt_blocks', body=doc)
            if res is None:
                failed = True
                continue
            adopted = res.get('adopted_prefixes')
            shipped += int(adopted) if isinstance(adopted, int) else 0
        self._bump('hot_handoffs')
        if shipped:
            self._bump('handoff_prefixes', shipped)
        if failed:
            self._bump('handoff_failures')
        logger.info('LB: hot-set handoff from %s: %d prefixes adopted '
                    'across %d survivor(s)', src, shipped, len(groups))

    # ------------------------------------------------------ controller sync

    def _sync_with_controller_once(self) -> None:
        with self._ts_lock:
            timestamps, self._request_timestamps = (
                self._request_timestamps, [])
        with self._health_lock:
            inflight = {u: h.outstanding for u, h in self._health.items()}
            draining = sorted(u for u, h in self._health.items()
                              if h.draining)
            probation = sorted(u for u, h in self._health.items()
                               if h.breaker.in_probation())
            replica_tp = dict(self._replica_tp)
        body = json.dumps({'request_timestamps': timestamps,
                           'replica_inflight': inflight,
                           'replica_draining': draining,
                           'replica_affinity':
                               self.policy.stats().get('per_replica', {}),
                           'tenant_qos': self.limiter.stats(),
                           'replica_latency': self._latency_summary(),
                           'replica_tp': replica_tp,
                           'replica_probation': probation,
                           'retry_budget':
                               self.retry_budget.remaining(),
                           'journal_age_s':
                               (None if self.journal is None
                                else self.journal.age_s()),
                           }).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=body, headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                payload = json.loads(r.read())
            self.policy.set_ready_replicas(
                payload.get('ready_replica_urls', []))
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('LB sync with controller failed: %s', e)
            # Keep serving the last known replica set.

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_with_controller_once()
            self._stop.wait(constants.lb_sync_interval())

    # --------------------------------------------------------- proxy path

    def _record_request(self) -> None:
        with self._ts_lock:
            self._request_timestamps.append(
                time.time())  # det-ok: wall-clock QPS feed (autoscaler)

    @staticmethod
    def _attempt_timeout(remaining: Optional[float]) -> float:
        """Replica connection timeout for one attempt: the client's
        remaining deadline budget when one exists, else the blanket
        default."""
        if remaining is None:
            return _DEFAULT_REPLICA_TIMEOUT
        return max(0.05, min(_DEFAULT_REPLICA_TIMEOUT, remaining))

    @staticmethod
    def _is_draining_response(resp) -> bool:
        return (resp.status == 503 and
                resp.getheader('X-SkyTpu-Draining') is not None)

    def _proxy_once(self, handler: BaseHTTPRequestHandler, replica: str,
                    body: Optional[bytes],
                    forward_shed: bool = True,
                    timeout: float = _DEFAULT_REPLICA_TIMEOUT) -> str:
        """Stream one request to one replica.  Returns 'unreachable'
        (retryable: nothing forwarded), 'shed' (replica answered 429 at
        admission and forward_shed is False — nothing forwarded, safe to
        retry elsewhere since the replica did no work), 'draining' (503
        + X-SkyTpu-Draining: the replica refuses new work — retry
        elsewhere), or 'ok' (a response line has been forwarded; errors
        past that point are no longer retryable)."""
        parsed = urllib.parse.urlsplit(replica)
        conn = HTTPConnection(parsed.hostname, parsed.port,
                              timeout=timeout)
        headers = {
            k: v for k, v in handler.headers.items()
            if k.lower() not in _HOP_BY_HOP and k.lower() != 'host'
        }
        headers['Host'] = parsed.netloc
        headers['Connection'] = 'close'
        try:
            conn.request(handler.command, handler.path, body=body,
                         headers=headers)
            resp = conn.getresponse()
        except (OSError, socket.timeout, HTTPException):
            # HTTPException covers a replica killed mid-status-line
            # (BadStatusLine): nothing was forwarded, so it is as
            # retryable as a refused connection.
            conn.close()
            return 'unreachable'
        if resp.status == 429 and not forward_shed:
            conn.close()
            return 'shed'
        if self._is_draining_response(resp):
            conn.close()
            self._mark_draining(replica, True)
            return 'draining'
        try:
            handler.send_response(resp.status, resp.reason)
            has_length = False
            for k, v in resp.getheaders():
                if k.lower() not in _HOP_BY_HOP:
                    handler.send_header(k, v)
                    has_length |= k.lower() == 'content-length'
            if not has_length:
                # Chunked replica response: http.client de-chunks on read,
                # so the body goes out raw — close-delimited framing is the
                # only way the client can find the end of it.
                handler.send_header('Connection', 'close')
                handler.close_connection = True
            handler.end_headers()
            while True:
                # read1: return as soon as ANY bytes are available (up
                # to the cap) instead of blocking until 64 KiB or EOF —
                # SSE/streamed token events must flow through per-event,
                # not in one burst at connection close.
                chunk = resp.read1(64 * 1024)
                if not chunk:
                    break
                handler.wfile.write(chunk)
                handler.wfile.flush()
        except (OSError, socket.timeout) as e:
            logger.warning('LB: client/replica stream broke mid-response: '
                           '%s', e)
        finally:
            conn.close()
        return 'ok'

    # --------------------------------------------- generate request routing

    @staticmethod
    def _parse_generate(path: str, command: str,
                        body: Optional[bytes]) -> Optional[dict]:
        """Classify a request for the failover-aware generate paths.

        Returns None for anything that is not a native generate POST
        with a JSON object body (those take the raw passthrough), else
        a route dict: payload, stream, deadline_s, and `resumable` —
        True only for token-prompt greedy bounded /generate streams,
        the combination whose continuation is reconstructible AND
        byte-deterministic."""
        if command != 'POST' or path.split('?', 1)[0] not in (
                '/generate', '/generate_text'):
            return None
        try:
            payload = json.loads(body or b'{}')
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(payload, dict):
            return None
        deadline = payload.get('deadline_s')
        deadline = (float(deadline)
                    if isinstance(deadline, (int, float)) and deadline > 0
                    else None)
        tokens = payload.get('tokens')
        max_new = payload.get('max_new_tokens')
        try:
            temperature = float(payload.get('temperature', 0.0))
        except (TypeError, ValueError):
            temperature = None
        resumable = (
            path.split('?', 1)[0] == '/generate' and
            bool(payload.get('stream')) and
            temperature == 0.0 and
            isinstance(tokens, list) and
            all(isinstance(t, int) for t in tokens) and
            isinstance(max_new, int) and max_new > 0
        )
        adapter = payload.get('adapter')
        context = RequestContext(
            tokens=(list(tokens) if isinstance(tokens, list) and
                    all(isinstance(t, int) for t in tokens) else None),
            adapter=adapter if isinstance(adapter, str) else None)
        tenant = payload.get('tenant_id')
        priority = payload.get('priority')
        return {'payload': payload, 'stream': bool(payload.get('stream')),
                'deadline_s': deadline, 'resumable': resumable,
                'path': path, 'context': context,
                'tenant_id': tenant if isinstance(tenant, str) else None,
                'priority': priority if isinstance(priority, str) else None}

    @staticmethod
    def _replica_headers(replica: str) -> Dict[str, str]:
        parsed = urllib.parse.urlsplit(replica)
        return {'Host': parsed.netloc, 'Connection': 'close',
                'Content-Type': 'application/json'}

    def _proxy_buffered_once(self, handler, replica: str, path: str,
                             payload: dict, timeout: float) -> str:
        """Non-stream generate: the replica response is FULLY buffered
        before anything is forwarded, so a replica dying mid-body stays
        retryable.  Returns 'done' | 'unreachable' | 'broken' | 'shed'
        | 'draining'."""
        parsed = urllib.parse.urlsplit(replica)
        conn = HTTPConnection(parsed.hostname, parsed.port,
                              timeout=timeout)
        body = json.dumps(payload).encode()
        t0 = self._clock()
        try:
            conn.request('POST', path, body=body,
                         headers=self._replica_headers(replica))
            resp = conn.getresponse()
        except (OSError, socket.timeout, HTTPException):
            conn.close()
            return 'unreachable'
        try:
            if resp.status == 429:
                return 'shed'
            if self._is_draining_response(resp):
                self._mark_draining(replica, True)
                return 'draining'
            try:
                data = resp.read()
            except (OSError, socket.timeout, HTTPException):
                return 'broken'
            declared = resp.getheader('Content-Length')
            if declared is not None and len(data) < int(declared):
                return 'broken'   # close-truncated body: retry elsewhere
            if resp.status == 200:
                # Whole-response latency: upper bound on TTFT, still
                # the right sign for SLO target tracking.
                self._record_ttft(replica, self._clock() - t0)
        finally:
            conn.close()
        try:
            handler.send_response(resp.status, resp.reason)
            for k, v in resp.getheaders():
                if k.lower() not in _HOP_BY_HOP and \
                        k.lower() != 'content-length':
                    handler.send_header(k, v)
            handler.send_header('Content-Length', str(len(data)))
            handler.end_headers()
            handler.wfile.write(data)
            handler.wfile.flush()
        except (OSError, socket.timeout):
            pass   # client went away; nothing left to do
        return 'done'

    def _proxy_stream_once(self, replica: str, path: str, payload: dict,
                           relay: _SSERelay, timeout: float) -> str:
        """One SSE generate attempt against one replica, relaying
        complete events through `relay` (the real client stream, or a
        `_BufferRelay` hedge arm).  Returns 'done' (final event
        forwarded), 'broken' (stream ended without one — failover
        material), 'unreachable', 'shed', 'draining', 'failed' (replica
        rejected a continuation — not retryable), 'client_gone', or
        'cancelled' (this hedge arm lost the race)."""
        parsed = urllib.parse.urlsplit(replica)
        conn = HTTPConnection(parsed.hostname, parsed.port,
                              timeout=timeout)
        body = json.dumps(payload).encode()
        t0 = self._clock()
        ttft_recorded = False
        try:
            conn.request('POST', path, body=body,
                         headers=self._replica_headers(replica))
            resp = conn.getresponse()
        except (OSError, socket.timeout, HTTPException):
            conn.close()
            return 'unreachable'
        try:
            if resp.status == 429:
                return 'shed'
            if self._is_draining_response(resp):
                self._mark_draining(replica, True)
                return 'draining'
            if resp.status != 200:
                if relay.headers_sent:
                    # A continuation was rejected (4xx/5xx): the stream
                    # cannot be resumed here or anywhere.
                    return 'failed'
                data = resp.read()
                relay.send_buffered_response(
                    resp.status, resp.reason, resp.getheaders(), data)
                return 'done'
            relay.send_headers(resp)
            buf = b''
            while True:
                try:
                    chunk = resp.read1(64 * 1024)
                except (OSError, socket.timeout, HTTPException):
                    return 'broken'
                if not chunk:
                    # EOF: a trailing partial event (no final \n\n) is
                    # NOT forwarded — failover re-produces it whole.
                    return 'broken'
                buf += chunk
                while b'\n\n' in buf:
                    event, buf = buf.split(b'\n\n', 1)
                    raw = event + b'\n\n'
                    if not ttft_recorded:
                        # First complete event out of this replica:
                        # its time-to-first-token, SLO feed.
                        ttft_recorded = True
                        self._record_ttft(replica, self._clock() - t0)
                    obj = self._parse_sse_event(event)
                    if obj is not None and obj.get('done'):
                        if relay.resumed:
                            # Stitched stream: the survivor's final
                            # event covers only its continuation —
                            # rewrite output_tokens to the whole
                            # generation the client actually received.
                            obj['output_tokens'] = list(relay.streamed)
                            obj['resumed'] = True
                            relay.emit_event(obj)
                        else:
                            relay.forward(raw)
                        return 'done'
                    if obj is not None and \
                            isinstance(obj.get('tokens'), list):
                        relay.note_tokens(obj['tokens'])
                    relay.forward(raw)
                    relay.chunks_forwarded += 1
        except _ClientGone:
            return 'client_gone'
        except _HedgeCancelled:
            return 'cancelled'
        finally:
            conn.close()

    @staticmethod
    def _parse_sse_event(event: bytes) -> Optional[dict]:
        for line in event.split(b'\n'):
            if line.startswith(b'data: '):
                try:
                    obj = json.loads(line[len(b'data: '):])
                except (ValueError, UnicodeDecodeError):
                    return None
                return obj if isinstance(obj, dict) else None
        return None

    def _continuation_payload(self, route: dict,
                              relay: _SSERelay,
                              remaining: Optional[float]) -> dict:
        orig = route['payload']
        cont = dict(orig)
        cont['tokens'] = list(orig['tokens']) + list(relay.streamed)
        cont['max_new_tokens'] = orig['max_new_tokens'] - \
            len(relay.streamed)
        if remaining is not None:
            cont['deadline_s'] = remaining
        return cont

    # ------------------------------------------------------ request handler

    def handle_request(self, handler: BaseHTTPRequestHandler) -> None:
        if handler.path.split('?', 1)[0] == '/lb/stats' and \
                handler.command == 'GET':
            self._serve_lb_stats(handler)
            return
        self._record_request()
        self._bump('requests')
        length = int(handler.headers.get('Content-Length', 0) or 0)
        body = handler.rfile.read(length) if length else None
        route = self._parse_generate(handler.path, handler.command, body)
        tenant = (route['tenant_id'] if route is not None
                  else self._peek_tenant(body))
        retry_after = self.limiter.check(tenant)
        if retry_after is not None:
            # Typed admission rejection at the LB edge: the tenant is
            # over its token-bucket rate; no replica does any work.
            self._bump('rate_limited')
            self._send_json(
                handler, 429,
                {'error': f'tenant {tenant or serve_qos.DEFAULT_TENANT!r}'
                          ' over its configured rate limit',
                 'error_class': 'rate_limited',
                 'retry_after_s': retry_after},
                headers={'Retry-After':
                         str(max(1, int(math.ceil(retry_after))))})
            return
        lease = self._batch_lease_acquire(route)
        try:
            if route is None:
                self._handle_passthrough(handler, body)
            elif route['stream']:
                self._handle_stream_generate(handler, route)
            else:
                self._handle_buffered_generate(handler, route)
        finally:
            self._batch_lease_release(lease)

    _JOURNAL_LEASE_PREFIX = 'lease:'

    def _batch_lease_acquire(self,
                             route: Optional[dict]) -> Optional[str]:
        """Journal a row lease for a batch-class generate: a warm
        restart can then tell exactly which rows died with the old
        process (adopted + released on restart; the coordinator's
        retry is the actual replay path)."""
        if route is None or route.get('priority') != 'batch':
            return None
        self._bump('batch_rows')
        payload = route.get('payload')
        rid = payload.get('request_id') if isinstance(payload,
                                                      dict) else None
        if not isinstance(rid, str) or not rid:
            return None
        with self._stats_lock:
            self._batch_leases.add(rid)
        if self.journal is not None:
            # Flushed, not fsync'd: losing a lease record costs one
            # adoption count, never a row (rows dedup by hash).
            self.journal.put(self._JOURNAL_LEASE_PREFIX + rid,
                             {'held': True})
        return rid

    def _batch_lease_release(self, rid: Optional[str]) -> None:
        if rid is None:
            return
        with self._stats_lock:
            self._batch_leases.discard(rid)
        if self.journal is not None:
            self.journal.put(self._JOURNAL_LEASE_PREFIX + rid,
                             {'held': False})

    @staticmethod
    def _peek_tenant(body: Optional[bytes]) -> Optional[str]:
        """Best-effort tenant_id from a passthrough JSON body (the
        /v1/* OpenAI paths accept tenant_id as an extension field) so
        LB rate limits cover every generate surface, not just the
        native routes."""
        if not body:
            return None
        try:
            payload = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(payload, dict):
            return None
        tenant = payload.get('tenant_id')
        return tenant if isinstance(tenant, str) else None

    def _deadline_clock(self, route: Optional[dict]):
        """Returns remaining() -> Optional[float]: the client's unspent
        deadline budget, decremented across every attempt."""
        deadline = route['deadline_s'] if route else None
        t0 = self._clock()

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return deadline - (self._clock() - t0)

        return remaining

    def _send_json(self, handler, code: int, payload: dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        try:
            msg = json.dumps(payload).encode()
            handler.send_response(code)
            handler.send_header('Content-Type', 'application/json')
            handler.send_header('Content-Length', str(len(msg)))
            for k, v in (headers or {}).items():
                handler.send_header(k, v)
            handler.end_headers()
            handler.wfile.write(msg)
        except (OSError, socket.timeout):
            pass

    def _no_replica_response(self, handler, deadline_spent: bool) -> None:
        # Typed on both branches (ISSUE 20 satellite: no untyped 5xx):
        # the deadline 504 is final (the client's budget is spent, a
        # retry cannot help), the no-replica 503 is retryable and says
        # when.
        if deadline_spent:
            self._bump('deadline_exhausted')
            self._send_json(handler, 504, {
                'error': 'deadline_s exhausted before any replica '
                         'completed the request',
                'error_class': 'deadline'})
        else:
            self._send_json(
                handler, 503,
                {'error': 'no ready replicas',
                 'error_class': 'no_replica',
                 'retry_after_s': self._RETRY_AFTER_S},
                headers={'Retry-After':
                         str(int(self._RETRY_AFTER_S))})

    def _handle_passthrough(self, handler, body: Optional[bytes]) -> None:
        """The original streaming proxy: raw byte relay (OpenAI SSE
        framing passes through untouched), retry only while nothing has
        been forwarded."""
        tried = set()
        shed_replica = None
        for _ in range(_MAX_ATTEMPTS):
            replica = self.policy.select_replica(
                exclude=self._routing_exclude(tried))
            # Passthrough traffic carries no parsed token prompt, so no
            # RequestContext: affinity policies fall back to load-only.
            if replica is None:
                break
            tried.add(replica)
            self._bump('attempts')
            self._adjust_outstanding(replica, 1)
            try:
                outcome = self._proxy_once(handler, replica, body,
                                           forward_shed=False)
                if outcome == 'ok':
                    self._rep(replica).breaker.record_success()
                    self.retry_budget.deposit()
                    return
                if outcome == 'shed':
                    # Admission-shed: the replica did no work — another
                    # replica may have headroom.
                    self._rep(replica).breaker.record_success()
                    shed_replica = replica
                    continue
                if outcome == 'draining':
                    continue
                self._rep(replica).breaker.record_failure()
                if not self._retry_budget_spend():
                    self._retry_budget_response(handler)
                    return
                logger.warning('LB: replica %s unreachable, retrying',
                               replica)
            finally:
                self._adjust_outstanding(replica, -1)
                self.policy.request_done(replica)
        if shed_replica is not None:
            # Every candidate shed: surface the 429 (+ Retry-After) to
            # the client.  Re-requesting is safe — a shed does no work.
            # No request_done here: the loop already paired this
            # replica's select_replica with its request_done, and an
            # unmatched decrement would corrupt LeastLoadPolicy's
            # outstanding counts exactly when the fleet is overloaded.
            if self._proxy_once(handler, shed_replica, body,
                                forward_shed=True) == 'ok':
                return
        self._no_replica_response(handler, deadline_spent=False)

    def _handle_buffered_generate(self, handler, route: dict) -> None:
        """Non-stream generate: buffered relay makes a replica death at
        ANY point retryable — nothing reaches the client until the
        replica's full response is in hand."""
        remaining = self._deadline_clock(route)
        tried = set()
        shed_replica = None
        had_break = False
        for _ in range(_MAX_ATTEMPTS):
            left = remaining()
            if left is not None and left <= 0:
                self._no_replica_response(handler, deadline_spent=True)
                return
            replica = self.policy.select_replica(
                exclude=self._routing_exclude(tried),
                context=route.get('context'))
            if replica is None:
                break
            tried.add(replica)
            self._bump('attempts')
            if had_break:
                self._bump('failovers')
            self._adjust_outstanding(replica, 1)
            try:
                outcome = self._proxy_buffered_once(
                    handler, replica, route['path'], route['payload'],
                    timeout=self._attempt_timeout(left))
            finally:
                self._adjust_outstanding(replica, -1)
                self.policy.request_done(replica)
            if outcome == 'done':
                self._rep(replica).breaker.record_success()
                self.retry_budget.deposit()
                return
            if outcome == 'shed':
                self._rep(replica).breaker.record_success()
                shed_replica = replica
                continue
            if outcome == 'draining':
                continue
            # unreachable / broken: connection-level failure.
            self._rep(replica).breaker.record_failure()
            had_break |= outcome == 'broken'
            if not self._retry_budget_spend():
                self._retry_budget_response(handler)
                return
            logger.warning('LB: replica %s %s, retrying elsewhere',
                           replica, outcome)
        if shed_replica is not None:
            if self._proxy_once(handler, shed_replica,
                                json.dumps(route['payload']).encode(),
                                forward_shed=True) == 'ok':
                return
        left = remaining()
        self._no_replica_response(
            handler, deadline_spent=left is not None and left <= 0)

    def _retry_budget_spend(self) -> bool:
        """Withdraw one failure-driven retry/hedge token; False means
        the budget is dry and the caller must answer the typed 503
        instead of piling on.  Shed/drain redirects are NOT charged —
        they cost the fleet nothing."""
        if self.retry_budget.try_withdraw():
            return True
        self._bump('retry_budget_exhausted')
        return False

    _RETRY_BUDGET_MSG = ('retry budget exhausted: the fleet is failing '
                         'faster than it is succeeding; not retrying')
    # Hint on retryable LB 503s (retry_budget / no_replica): the
    # reserve trickle refills ~1 token/10 s at defaults, and probe
    # rounds re-admit replicas on the same order — batch coordinators
    # and interactive clients both honor it.
    _RETRY_AFTER_S = 1.0

    def _retry_budget_response(self, handler) -> None:
        """The typed, retryable 503 every budget-dry path answers —
        one shape (error_class + Retry-After) on the buffered, stream,
        and passthrough paths alike (ISSUE 20 satellite)."""
        self._send_json(
            handler, 503,
            {'error': self._RETRY_BUDGET_MSG,
             'error_class': 'retry_budget',
             'retry_after_s': self._RETRY_AFTER_S},
            headers={'Retry-After': str(int(self._RETRY_AFTER_S))})

    def _stream_budget_exhausted(self, handler, relay: _SSERelay) -> None:
        if relay.headers_sent:
            relay.emit_error_event(self._RETRY_BUDGET_MSG, 'retry_budget')
        else:
            self._retry_budget_response(handler)

    def _attempt_stream(self, replica: str, route: dict, payload: dict,
                        relay, timeout: float) -> str:
        """One tracked stream attempt: counters + outstanding + policy
        accounting around _proxy_stream_once (shared by the direct path
        and each hedge arm's thread)."""
        self._bump('attempts')
        self._adjust_outstanding(replica, 1)
        try:
            return self._proxy_stream_once(
                replica, route['path'], payload, relay, timeout)
        finally:
            self._adjust_outstanding(replica, -1)
            self.policy.request_done(replica)

    def _hedged_attempt(self, primary: str, route: dict, relay: _SSERelay,
                        tried: set, left: Optional[float]):
        """TTFT hedge around the FIRST attempt of a resumable greedy
        stream.  The primary streams into a buffer; if its first event
        misses the hedge deadline (and the retry budget allows), the
        request is issued to the next-best replica and whichever arm
        produces a first event is promoted to the client stream — the
        loser is cancelled.  Returns (outcome, winning_replica);
        `tried` gains every replica an arm touched."""
        any_first = threading.Event()
        results: Dict[str, str] = {}

        def run_arm(url: str, buf: '_BufferRelay') -> None:
            results[url] = self._attempt_stream(
                url, route, route['payload'], buf,
                self._attempt_timeout(left))
            any_first.set()   # completion (even a failure) wakes the race

        p_buf = _BufferRelay(relay, any_first.set)
        p_thread = threading.Thread(
            target=run_arm, args=(primary, p_buf), daemon=True,
            name='lb-hedge-primary')
        p_thread.start()
        secondary = None
        s_buf = None
        s_thread = None
        if not p_buf.first_event.wait(self._hedge_s) and \
                p_thread.is_alive():
            # Hedge deadline passed with no first byte.  A hedge is a
            # speculative retry: it spends a retry-budget token, and a
            # dry budget silently skips the hedge (the primary is still
            # running — nothing to fail).
            if self._retry_budget_spend():
                secondary = self.policy.select_replica(
                    exclude=self._routing_exclude(tried),
                    context=route.get('context'))
            if secondary is not None:
                tried.add(secondary)
                self._bump('hedges')
                s_buf = _BufferRelay(relay, any_first.set)
                s_thread = threading.Thread(
                    target=run_arm, args=(secondary, s_buf),
                    daemon=True, name='lb-hedge-secondary')
                s_thread.start()
                while not (p_buf.first_event.is_set() or
                           s_buf.first_event.is_set() or
                           (not p_thread.is_alive() and
                            not s_thread.is_alive())):
                    any_first.wait(0.02)
                    any_first.clear()
        # Pick the winner: first byte beats no byte; the primary wins
        # ties (deterministic, and its buffer is never behind).
        if s_buf is not None and s_buf.first_event.is_set() and \
                not p_buf.first_event.is_set():
            winner, w_buf, w_thread = secondary, s_buf, s_thread
            loser_buf, loser_thread = p_buf, p_thread
            self._bump('hedge_wins')
        else:
            winner, w_buf, w_thread = primary, p_buf, p_thread
            loser_buf, loser_thread = s_buf, s_thread
        if loser_buf is not None:
            loser_buf.cancel()
            self._bump('hedge_cancelled')
        try:
            w_buf.promote()
        except _ClientGone:
            return 'client_gone', winner
        w_thread.join()
        if loser_thread is not None:
            # The loser unwinds on its next write (HedgeCancelled) or
            # at stream EOF; bounded by the attempt timeout either way.
            loser_thread.join(timeout=self._attempt_timeout(left))
        relay.chunks_forwarded = w_buf.chunks_forwarded
        relay.resumed = w_buf.resumed
        return results.get(winner, 'broken'), winner

    def _handle_stream_generate(self, handler, route: dict) -> None:
        """SSE generate with mid-stream failover: resumable streams are
        continued on a survivor byte-identically; non-resumable streams
        that already relayed tokens fail fast with a typed error.  The
        first attempt of a resumable stream is hedged when
        SKYTPU_LB_HEDGE_MS is set."""
        remaining = self._deadline_clock(route)
        relay = _SSERelay(handler)
        payload = route['payload']
        tried = set()
        shed_replica = None
        first_attempt = True
        for _ in range(_MAX_ATTEMPTS):
            left = remaining()
            if left is not None and left <= 0:
                break
            replica = self.policy.select_replica(
                exclude=self._routing_exclude(tried),
                context=route.get('context'))
            if replica is None:
                break
            tried.add(replica)
            resuming = relay.resumed
            if first_attempt and route['resumable'] and \
                    self._hedge_s > 0:
                outcome, replica = self._hedged_attempt(
                    replica, route, relay, tried, left)
            else:
                outcome = self._attempt_stream(
                    replica, route, payload, relay,
                    self._attempt_timeout(left))
            first_attempt = False
            if outcome == 'done':
                self._rep(replica).breaker.record_success()
                self.retry_budget.deposit()
                if resuming:
                    self._bump('streams_resumed')
                return
            if outcome == 'client_gone':
                return
            if outcome == 'failed':
                relay.emit_error_event(
                    'replica rejected the failover continuation',
                    'lb_failover')
                return
            if outcome == 'shed':
                self._rep(replica).breaker.record_success()
                shed_replica = replica
                continue
            if outcome == 'draining':
                continue
            # unreachable / broken.
            self._rep(replica).breaker.record_failure()
            if outcome == 'unreachable':
                if not self._retry_budget_spend():
                    self._stream_budget_exhausted(handler, relay)
                    return
                continue
            # broken: the replica died mid-stream.
            if relay.chunks_forwarded == 0 and not relay.headers_sent:
                if not self._retry_budget_spend():
                    self._stream_budget_exhausted(handler, relay)
                    return
                continue   # nothing reached the client: plain retry
            if not route['resumable']:
                if relay.chunks_forwarded == 0:
                    # Headers out but no tokens: a fresh replay is
                    # observationally identical for the client.
                    if not self._retry_budget_spend():
                        self._stream_budget_exhausted(handler, relay)
                        return
                    continue
                # Tokens already relayed and the continuation is not
                # reconstructible (sampled / unbounded / text prompt):
                # fail FAST with a typed error, never a silent
                # truncation or a diverging replay.
                self._bump('non_resumable_failures')
                relay.emit_error_event(
                    'replica died mid-stream; request is not resumable '
                    '(requires temperature=0, token prompt and '
                    'max_new_tokens)', 'non_resumable')
                return
            if not self._retry_budget_spend():
                self._stream_budget_exhausted(handler, relay)
                return
            self._bump('failovers')
            left = remaining()
            if left is not None and left <= 0:
                break
            if len(relay.streamed) >= route['payload']['max_new_tokens']:
                # Died after the last token but before the final event:
                # everything was generated — synthesize the terminal.
                relay.resumed = True
                try:
                    relay.emit_event({
                        'done': True, 'resumed': True,  # wire-ok: client-facing API field
                        'output_tokens': list(relay.streamed),
                        'finish_reason': 'length',
                        'ttft_s': 0.0, 'latency_s': 0.0})
                except _ClientGone:
                    pass
                self._bump('streams_resumed')
                return
            payload = self._continuation_payload(route, relay, left)
            relay.resumed = True
            logger.warning('LB: replica %s died mid-stream; resuming '
                           'at token %d on a survivor', replica,
                           len(relay.streamed))
        # No replica finished the stream.
        left = remaining()
        if relay.headers_sent:
            relay.emit_error_event(
                'deadline_s exhausted during failover'
                if left is not None and left <= 0 else
                'no replica available to resume the stream',
                'lb_failover')
            return
        if shed_replica is not None:
            if self._proxy_once(handler, shed_replica,
                                json.dumps(route['payload']).encode(),
                                forward_shed=True) == 'ok':
                return
        self._no_replica_response(
            handler, deadline_spent=left is not None and left <= 0)

    # --------------------------------------------------------------- stats

    def lb_stats(self) -> dict:
        with self._health_lock:
            breaker_opens = sum(h.breaker.open_count
                                for h in self._health.values())
            open_now = sorted(u for u, h in self._health.items()
                              if not h.breaker.available())
            draining = sorted(u for u, h in self._health.items()
                              if h.draining)
            outstanding = {u: h.outstanding
                           for u, h in self._health.items()
                           if h.outstanding}
            probation = sorted(u for u, h in self._health.items()
                               if h.breaker.in_probation())
            unverified = sorted(self._adopted_unverified)
            tiers = [dict(t) for t in self._replica_host_tier.values()]
        # Fleet host-tier aggregate: occupancy + spill/restore traffic
        # summed over tier-enabled replicas, hit rate averaged.
        host_tier = {'replicas': 0, 'bytes': 0, 'spills': 0,
                     'restores': 0, 'in_flight': 0, 'evictions': 0,
                     'restore_hit_rate': 0.0}
        rates: List[float] = []
        for ht in tiers:
            if not ht.get('enabled'):
                continue
            host_tier['replicas'] += 1
            host_tier['bytes'] += int(ht.get('bytes') or 0)
            host_tier['spills'] += int(ht.get('spills') or 0)
            host_tier['restores'] += int(ht.get('restores') or 0)
            host_tier['in_flight'] += int(ht.get('in_flight') or 0)
            host_tier['evictions'] += int(ht.get('evictions') or 0)
            rate = ht.get('restore_hit_rate')
            if isinstance(rate, (int, float)):
                rates.append(float(rate))
        if rates:
            host_tier['restore_hit_rate'] = sum(rates) / len(rates)
        with self._stats_lock:
            counters = dict(self._counters)
            batch_inflight = len(self._batch_leases)
        counters.update({
            'batch_rows_inflight': batch_inflight,
            'kv_host_tier': host_tier,
            'breaker_opens': breaker_opens,  # wire-ok: operator metrics surface
            'breaker_open_now': open_now,
            'draining_replicas': draining,
            'outstanding': outstanding,  # wire-ok: operator metrics surface
            'ready_replicas': list(self.policy.ready_replicas),  # wire-ok: operator metrics surface
            'policy': self.policy.stats(),
            'qos': self.limiter.stats(),  # wire-ok: operator metrics surface
            'replica_latency': self._latency_summary(),  # wire-ok: operator metrics surface
            'probation_replicas': probation,
            'retry_budget_remaining': self.retry_budget.remaining(),
            'journal_age_s': (None if self.journal is None
                              else self.journal.age_s()),
            'adopted_unverified': unverified,
        })
        return counters

    def _serve_lb_stats(self, handler) -> None:
        self._send_json(handler, 200, self.lb_stats())

    # -------------------------------------------------------------- server

    def run(self) -> None:
        lb = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                pass

            def _any(self):
                try:
                    lb.handle_request(self)
                except (OSError, socket.timeout):
                    pass

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _any
            do_HEAD = do_OPTIONS = _any

        if self.controller_url is not None:
            sync_thread = threading.Thread(target=self._sync_loop,
                                           daemon=True, name='lb-sync')
            sync_thread.start()
        probe_thread = threading.Thread(target=self._probe_loop,
                                        daemon=True, name='lb-probe')
        probe_thread.start()

        class _Server(self._server_cls):
            # Default listen backlog (5) RSTs connections during
            # arrival bursts; user traffic funnels through this port.
            request_queue_size = 128

        self._httpd = _Server(('0.0.0.0', self.port), Handler)
        self._httpd.daemon_threads = True
        logger.info('Load balancer listening on :%d -> controller %s',
                    self.port, self.controller_url)
        self._httpd.serve_forever(poll_interval=0.2)

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()


def make_load_balancer(controller_url: Optional[str], port: int,
                       policy_name: str) -> SkyTpuLoadBalancer:
    """Build an LB with the journal wired from SKYTPU_LB_JOURNAL (empty
    = no journal = cold restarts).  This is the supervisor's factory:
    each restart re-runs it, and journal re-adoption happens in the LB
    constructor."""
    policy = LoadBalancingPolicy.make(policy_name)
    journal = None
    path = constants.lb_journal_path()
    if path:
        journal = LBJournal(
            os.path.expanduser(path), clock=time.monotonic,
            compact_every=constants.lb_journal_compact_every())
    return SkyTpuLoadBalancer(controller_url, port, policy,
                              journal=journal)


def run_load_balancer(controller_url: str, port: int,
                      policy_name: str) -> None:
    make_load_balancer(controller_url, port, policy_name).run()
