"""HTTP load balancer: streams user traffic to ready replicas.

Parity: sky/serve/load_balancer.py:22-229 (FastAPI/httpx reverse proxy
with controller sync + retry across replicas).  Built on stdlib
ThreadingHTTPServer + http.client so replica responses stream through in
chunks (LLM serving needs streaming) without extra dependencies.
"""
import json
import socket
import threading
import time
import urllib.parse
import urllib.request
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from skypilot_tpu import logsys
from skypilot_tpu.serve import constants
from skypilot_tpu.serve.load_balancing_policies import LoadBalancingPolicy

logger = logsys.init_logger(__name__)

_HOP_BY_HOP = {
    'connection', 'keep-alive', 'proxy-authenticate',
    'proxy-authorization', 'te', 'trailers', 'transfer-encoding', 'upgrade'
}
_MAX_ATTEMPTS = 3


class SkyTpuLoadBalancer:

    def __init__(self, controller_url: str, port: int,
                 policy: LoadBalancingPolicy):
        self.controller_url = controller_url
        self.port = port
        self.policy = policy
        self._request_timestamps: List[float] = []
        self._ts_lock = threading.Lock()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------ controller sync

    def _sync_with_controller_once(self) -> None:
        with self._ts_lock:
            timestamps, self._request_timestamps = (
                self._request_timestamps, [])
        body = json.dumps({'request_timestamps': timestamps}).encode()
        req = urllib.request.Request(
            self.controller_url + '/controller/load_balancer_sync',
            data=body, headers={'Content-Type': 'application/json'})
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                payload = json.loads(r.read())
            self.policy.set_ready_replicas(
                payload.get('ready_replica_urls', []))
        except Exception as e:  # pylint: disable=broad-except
            logger.warning('LB sync with controller failed: %s', e)
            # Keep serving the last known replica set.

    def _sync_loop(self) -> None:
        while not self._stop.is_set():
            self._sync_with_controller_once()
            self._stop.wait(constants.lb_sync_interval())

    # --------------------------------------------------------- proxy path

    def _record_request(self) -> None:
        with self._ts_lock:
            self._request_timestamps.append(time.time())

    def _proxy_once(self, handler: BaseHTTPRequestHandler, replica: str,
                    body: Optional[bytes],
                    forward_shed: bool = True) -> str:
        """Stream one request to one replica.  Returns 'unreachable'
        (retryable: nothing forwarded), 'shed' (replica answered 429 at
        admission and forward_shed is False — nothing forwarded, safe to
        retry elsewhere since the replica did no work), or 'ok' (a
        response line has been forwarded; errors past that point are no
        longer retryable)."""
        parsed = urllib.parse.urlsplit(replica)
        conn = HTTPConnection(parsed.hostname, parsed.port, timeout=120)
        headers = {
            k: v for k, v in handler.headers.items()
            if k.lower() not in _HOP_BY_HOP and k.lower() != 'host'
        }
        headers['Host'] = parsed.netloc
        headers['Connection'] = 'close'
        try:
            conn.request(handler.command, handler.path, body=body,
                         headers=headers)
            resp = conn.getresponse()
        except (OSError, socket.timeout):
            conn.close()
            return 'unreachable'
        if resp.status == 429 and not forward_shed:
            conn.close()
            return 'shed'
        try:
            handler.send_response(resp.status, resp.reason)
            has_length = False
            for k, v in resp.getheaders():
                if k.lower() not in _HOP_BY_HOP:
                    handler.send_header(k, v)
                    has_length |= k.lower() == 'content-length'
            if not has_length:
                # Chunked replica response: http.client de-chunks on read,
                # so the body goes out raw — close-delimited framing is the
                # only way the client can find the end of it.
                handler.send_header('Connection', 'close')
                handler.close_connection = True
            handler.end_headers()
            while True:
                # read1: return as soon as ANY bytes are available (up
                # to the cap) instead of blocking until 64 KiB or EOF —
                # SSE/streamed token events must flow through per-event,
                # not in one burst at connection close.
                chunk = resp.read1(64 * 1024)
                if not chunk:
                    break
                handler.wfile.write(chunk)
                handler.wfile.flush()
        except (OSError, socket.timeout) as e:
            logger.warning('LB: client/replica stream broke mid-response: '
                           '%s', e)
        finally:
            conn.close()
        return 'ok'

    def handle_request(self, handler: BaseHTTPRequestHandler) -> None:
        self._record_request()
        length = int(handler.headers.get('Content-Length', 0) or 0)
        body = handler.rfile.read(length) if length else None
        tried = set()
        shed_replica = None
        for _ in range(_MAX_ATTEMPTS):
            replica = self.policy.select_replica()
            if replica is None or replica in tried:
                break
            tried.add(replica)
            try:
                outcome = self._proxy_once(handler, replica, body,
                                           forward_shed=False)
                if outcome == 'ok':
                    return
                if outcome == 'shed':
                    # Admission-shed: the replica did no work — another
                    # replica may have headroom.
                    shed_replica = replica
                    continue
                logger.warning('LB: replica %s unreachable, retrying',
                               replica)
            finally:
                self.policy.request_done(replica)
        if shed_replica is not None:
            # Every candidate shed: surface the 429 (+ Retry-After) to
            # the client.  Re-requesting is safe — a shed does no work.
            # No request_done here: the loop already paired this
            # replica's select_replica with its request_done, and an
            # unmatched decrement would corrupt LeastLoadPolicy's
            # outstanding counts exactly when the fleet is overloaded.
            if self._proxy_once(handler, shed_replica, body,
                                forward_shed=True) == 'ok':
                return
        handler.send_response(503)
        msg = b'{"error": "no ready replicas"}'
        handler.send_header('Content-Type', 'application/json')
        handler.send_header('Content-Length', str(len(msg)))
        handler.end_headers()
        handler.wfile.write(msg)

    # -------------------------------------------------------------- server

    def run(self) -> None:
        lb = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                pass

            def _any(self):
                try:
                    lb.handle_request(self)
                except (OSError, socket.timeout):
                    pass

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _any
            do_HEAD = do_OPTIONS = _any

        sync_thread = threading.Thread(target=self._sync_loop, daemon=True,
                                       name='lb-sync')
        sync_thread.start()
        class _Server(ThreadingHTTPServer):
            # Default listen backlog (5) RSTs connections during
            # arrival bursts; user traffic funnels through this port.
            request_queue_size = 128

        self._httpd = _Server(('0.0.0.0', self.port), Handler)
        self._httpd.daemon_threads = True
        logger.info('Load balancer listening on :%d -> controller %s',
                    self.port, self.controller_url)
        self._httpd.serve_forever(poll_interval=0.2)

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()


def run_load_balancer(controller_url: str, port: int,
                      policy_name: str) -> None:
    policy = LoadBalancingPolicy.make(policy_name)
    SkyTpuLoadBalancer(controller_url, port, policy).run()
