"""LB-side QoS: per-tenant token-bucket rate limiting.

The engine-side QoS plane (`infer/qos.py`) makes overload *fair*; this
module keeps overload *bounded* before it ever reaches a replica: each
tenant gets a token bucket at the load balancer, and a tenant over its
rate receives a typed 429 with a `Retry-After` hint instead of queueing
into everyone else's tail.  Counters feed `/lb/stats` and are synced to
the controller so `GET /controller/state` shows who is being limited
(the same path PR 7 used for affinity counters).

Determinism: the clock is injected (the LB passes its own `clock`
seam), so tests drive buckets with a fake clock — no wall-clock reads
in here (analysis/determinism.py scope).
"""
import threading
from typing import Any, Dict, Optional

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.serve import constants

# Tenant key for requests that carry no tenant_id: they share one
# bucket at the default rate rather than bypassing limiting.
DEFAULT_TENANT = '_default'


class TokenBucket:
    """Classic token bucket: `rate` tokens/second refill up to `burst`
    capacity; try_acquire() spends one token or returns the seconds
    until one is available (the 429's Retry-After)."""

    def __init__(self, rate: float, burst: float, clock) -> None:
        if rate <= 0:
            raise ValueError(f'rate must be > 0 (got {rate})')
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def try_acquire(self, n: float = 1.0) -> Optional[float]:
        """None = admitted (token spent); else seconds until `n`
        tokens will have refilled (never negative)."""
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return None
        return max(0.0, (n - self._tokens) / self.rate)


class TenantRateLimiter:
    """Per-tenant token buckets with admitted/rejected counters.

    Rates resolve per tenant: an explicit entry in `tenant_rates`
    wins; otherwise `default_rate` applies; a resolved rate <= 0 means
    UNLIMITED for that tenant (check() always admits).  Buckets are
    created lazily and bounded (beyond `max_tenants` distinct ids the
    overflow shares one bucket — a tenant-id-spraying client must not
    grow LB memory without limit)."""

    _OVERFLOW = '_overflow'

    def __init__(self, default_rate: Optional[float] = None,
                 default_burst: Optional[float] = None,
                 tenant_rates: Optional[Dict[str, float]] = None,
                 clock=None, max_tenants: int = 1024) -> None:
        assert clock is not None, 'inject the LB clock seam'
        self._clock = clock
        self._default_rate = (constants.qos_default_rate()
                              if default_rate is None else default_rate)
        self._default_burst = (constants.qos_default_burst()
                               if default_burst is None else default_burst)
        self._tenant_rates = (constants.qos_tenant_rates()
                              if tenant_rates is None else
                              dict(tenant_rates))
        self._max_tenants = max_tenants
        self._buckets: Dict[str, Optional[TokenBucket]] = {}  # guarded-by: _lock
        self._counters: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.qos.limiter._lock')

    def _rate_for(self, tenant: str) -> float:
        return float(self._tenant_rates.get(tenant, self._default_rate))

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:  # locked: _lock
        if tenant not in self._buckets and \
                len(self._buckets) >= self._max_tenants:
            tenant = self._OVERFLOW
        if tenant not in self._buckets:
            rate = self._rate_for(tenant)
            self._buckets[tenant] = (
                TokenBucket(rate, self._default_burst, self._clock)
                if rate > 0 else None)     # None = unlimited
        return self._buckets[tenant]

    def check(self, tenant_id: Optional[str]) -> Optional[float]:
        """One request from `tenant_id`: None = admitted, else the
        Retry-After seconds for the typed 429."""
        tenant = tenant_id if tenant_id else DEFAULT_TENANT
        with self._lock:
            bucket = self._bucket(tenant)
            retry_after = None if bucket is None else bucket.try_acquire()
            row = self._counters.setdefault(
                tenant if tenant in self._buckets else self._OVERFLOW,
                {'admitted': 0, 'rejected': 0})
            row['admitted' if retry_after is None else 'rejected'] += 1
            return retry_after

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'default_rate': self._default_rate,
                'tenants': {t: dict(c)
                            for t, c in self._counters.items()},
            }
