"""LB-side QoS: per-tenant token-bucket rate limiting.

The engine-side QoS plane (`infer/qos.py`) makes overload *fair*; this
module keeps overload *bounded* before it ever reaches a replica: each
tenant gets a token bucket at the load balancer, and a tenant over its
rate receives a typed 429 with a `Retry-After` hint instead of queueing
into everyone else's tail.  Counters feed `/lb/stats` and are synced to
the controller so `GET /controller/state` shows who is being limited
(the same path PR 7 used for affinity counters).

Determinism: the clock is injected (the LB passes its own `clock`
seam), so tests drive buckets with a fake clock — no wall-clock reads
in here (analysis/determinism.py scope).
"""
import threading
from typing import Any, Dict, Optional

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.serve import constants

# Tenant key for requests that carry no tenant_id: they share one
# bucket at the default rate rather than bypassing limiting.
DEFAULT_TENANT = '_default'


class TokenBucket:
    """Classic token bucket: `rate` tokens/second refill up to `burst`
    capacity; try_acquire() spends one token or returns the seconds
    until one is available (the 429's Retry-After)."""

    def __init__(self, rate: float, burst: float, clock) -> None:
        if rate <= 0:
            raise ValueError(f'rate must be > 0 (got {rate})')
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def try_acquire(self, n: float = 1.0) -> Optional[float]:
        """None = admitted (token spent); else seconds until `n`
        tokens will have refilled (never negative)."""
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return None
        return max(0.0, (n - self._tokens) / self.rate)

    def level(self) -> float:
        """Current token level WITHOUT refilling (journal snapshot)."""
        return self._tokens

    def set_level(self, tokens: float) -> None:
        """Re-adopt a journalled level (clamped to capacity); resets
        the refill clock to now so no phantom refill accrues for the
        downtime."""
        self._tokens = max(0.0, min(self.burst, float(tokens)))
        self._last = self._clock()


class TenantRateLimiter:
    """Per-tenant token buckets with admitted/rejected counters.

    Rates resolve per tenant: an explicit entry in `tenant_rates`
    wins; otherwise `default_rate` applies; a resolved rate <= 0 means
    UNLIMITED for that tenant (check() always admits).  Buckets are
    created lazily and bounded (beyond `max_tenants` distinct ids the
    overflow shares one bucket — a tenant-id-spraying client must not
    grow LB memory without limit)."""

    _OVERFLOW = '_overflow'

    def __init__(self, default_rate: Optional[float] = None,
                 default_burst: Optional[float] = None,
                 tenant_rates: Optional[Dict[str, float]] = None,
                 clock=None, max_tenants: int = 1024) -> None:
        assert clock is not None, 'inject the LB clock seam'
        self._clock = clock
        self._default_rate = (constants.qos_default_rate()
                              if default_rate is None else default_rate)
        self._default_burst = (constants.qos_default_burst()
                               if default_burst is None else default_burst)
        self._tenant_rates = (constants.qos_tenant_rates()
                              if tenant_rates is None else
                              dict(tenant_rates))
        self._max_tenants = max_tenants
        self._buckets: Dict[str, Optional[TokenBucket]] = {}  # guarded-by: _lock
        self._counters: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.qos.limiter._lock')

    def _rate_for(self, tenant: str) -> float:
        return float(self._tenant_rates.get(tenant, self._default_rate))

    def _bucket(self, tenant: str) -> Optional[TokenBucket]:  # locked: _lock
        if tenant not in self._buckets and \
                len(self._buckets) >= self._max_tenants:
            tenant = self._OVERFLOW
        if tenant not in self._buckets:
            rate = self._rate_for(tenant)
            self._buckets[tenant] = (
                TokenBucket(rate, self._default_burst, self._clock)
                if rate > 0 else None)     # None = unlimited
        return self._buckets[tenant]

    def check(self, tenant_id: Optional[str]) -> Optional[float]:
        """One request from `tenant_id`: None = admitted, else the
        Retry-After seconds for the typed 429."""
        tenant = tenant_id if tenant_id else DEFAULT_TENANT
        with self._lock:
            bucket = self._bucket(tenant)
            retry_after = None if bucket is None else bucket.try_acquire()
            row = self._counters.setdefault(
                tenant if tenant in self._buckets else self._OVERFLOW,
                {'admitted': 0, 'rejected': 0})
            row['admitted' if retry_after is None else 'rejected'] += 1
            return retry_after

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                'default_rate': self._default_rate,
                'tenants': {t: dict(c)
                            for t, c in self._counters.items()},
            }

    def snapshot(self) -> Dict[str, Any]:
        """Journalable bucket levels + counters (JSON-serialisable).
        Unlimited tenants (bucket None) carry a null level."""
        with self._lock:
            return {
                'levels': {t: (None if b is None else b.level())
                           for t, b in self._buckets.items()},
                'counters': {t: dict(c)
                             for t, c in self._counters.items()},
            }

    def restore(self, snap: Dict[str, Any]) -> None:
        """Re-adopt journalled bucket levels + counters: a tenant that
        burned its burst before the LB died must not get a fresh burst
        from the restart."""
        with self._lock:
            for tenant, level in (snap.get('levels') or {}).items():
                bucket = self._bucket(tenant)
                if bucket is not None and level is not None:
                    bucket.set_level(float(level))
            for tenant, row in (snap.get('counters') or {}).items():
                self._counters[tenant] = {
                    'admitted': int(row.get('admitted', 0)),
                    'rejected': int(row.get('rejected', 0))}


class RetryBudget:
    """Finagle-style retry budget for a replica set: retries and
    mid-stream failovers WITHDRAW a token; completed requests DEPOSIT
    ``ratio`` tokens (refill proportional to successes), plus a small
    constant ``reserve_per_s`` trickle so a cold fleet can still retry.
    When the bucket is dry the LB answers a typed 503
    (`error_class='retry_budget'`) instead of amplifying a brownout
    into a retry storm — with ratio=0.2 the fleet can never spend more
    than ~20% extra attempts on top of its successful throughput.

    Starts FULL (cap tokens): a fresh LB facing a flaky replica must be
    able to retry immediately; the budget only bites under sustained
    failure.  Clock injected; thread-safe."""

    def __init__(self, ratio: float = 0.2, reserve_per_s: float = 0.1,
                 cap: float = 100.0, clock=None) -> None:
        assert clock is not None, 'inject the LB clock seam'
        self.ratio = float(ratio)
        self.reserve_per_s = float(reserve_per_s)
        self.cap = float(cap)
        self._clock = clock
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.qos.retry_budget._lock')
        self._tokens = self.cap  # guarded-by: _lock
        self._last = clock()  # guarded-by: _lock (reserve-refill clock)

    def _refill(self) -> None:  # locked: _lock
        now = self._clock()
        self._tokens = min(
            self.cap,
            self._tokens + (now - self._last) * self.reserve_per_s)
        self._last = now

    def deposit(self) -> None:
        """One request completed successfully: earn `ratio` retries."""
        with self._lock:
            self._refill()
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_withdraw(self) -> bool:
        """Spend one retry/hedge token.  False = budget exhausted: the
        caller must fail the request with error_class='retry_budget'
        rather than pile on."""
        with self._lock:
            self._refill()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def remaining(self) -> float:
        with self._lock:
            self._refill()
            return self._tokens

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            self._refill()
            return {'tokens': self._tokens}

    def restore(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self._tokens = max(
                0.0, min(self.cap, float(snap.get('tokens', self.cap))))
            self._last = self._clock()
