"""Client↔controller plumbing for the serve plane.

Parity: sky/serve/serve_utils.py — the ServeCodeGen twin (client executes
short python programs on the serve-controller host), service name
validation, and status formatting.
"""
import enum
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.podlet import codegen as podlet_codegen

parse_result = podlet_codegen.parse_result


class UpdateMode(enum.Enum):
    """How `serve.update` replaces old-version replicas.

    Parity: sky/serve/serve_utils.py UpdateMode (consumed at
    sky/serve/core.py:309).

    ROLLING    — bounded surge: launch ONE new-version replica at a time
                 and drain an old one as each turns READY; total capacity
                 stays near min_replicas throughout.
    BLUE_GREEN — bring up a FULL new-version fleet first; old replicas
                 drain only after every new one is READY (2x resources
                 during the update, zero capacity dip).
    """
    ROLLING = 'rolling'
    BLUE_GREEN = 'blue_green'

_IMPORTS = ('from skypilot_tpu.serve import serve_state\n'
            'from skypilot_tpu.serve import constants as serve_constants')


def _wrap(body: str) -> str:
    return podlet_codegen.wrap_python(body, _IMPORTS)


_SERVICE_NAME_RE = re.compile(r'^[a-z]([a-z0-9-]{0,38}[a-z0-9])?$')


def validate_service_name(name: str) -> None:
    if not _SERVICE_NAME_RE.match(name):
        raise exceptions.InvalidTaskError(
            f'Service name {name!r} is invalid: must match '
            f'{_SERVICE_NAME_RE.pattern} (it prefixes replica cluster '
            'names).')


def generate_service_name(task_name: Optional[str]) -> str:
    import uuid
    base = re.sub(r'[^a-z0-9-]', '-', (task_name or 'service').lower())
    base = re.sub(r'-+', '-', base).strip('-') or 'service'
    if not base[0].isalpha():
        base = 's-' + base
    return f'{base[:20]}-{uuid.uuid4().hex[:4]}'


class ServeCodeGen:
    """Shell commands to run on the serve-controller host."""

    @staticmethod
    def get_service_status() -> str:
        return _wrap(
            '_emit(json.loads(serve_state.services_as_json()))\n')

    @staticmethod
    def terminate_services(names: Optional[List[str]],
                           purge: bool = False) -> str:
        """None => all services.  Writes terminate signal files; with
        purge, services whose controller process is dead (e.g.
        CONTROLLER_FAILED — nothing left to consume the signal) have their
        rows removed directly."""
        body = (
            f'import signal as _sig\n'
            f'names = {names!r}\n'
            f'if names is None:\n'
            f'    names = [s["name"] for s in serve_state.get_services()]\n'
            f'sigdir = os.path.expanduser(serve_constants.SIGNAL_DIR)\n'
            f'os.makedirs(sigdir, exist_ok=True)\n'
            f'touched = []\n'
            f'for n in names:\n'
            f'    svc = serve_state.get_service(n)\n'
            f'    if svc is None:\n'
            f'        continue\n'
            f'    pid_alive = True\n'
            f'    try:\n'
            f'        os.kill(svc["controller_pid"], 0)\n'
            f'    except (OSError, TypeError):\n'
            f'        pid_alive = False\n'
            f'    if {purge!r} and not pid_alive:\n'
            f'        serve_state.remove_service(n)\n'
            f'    else:\n'
            f'        open(os.path.join(sigdir, n), "w").write('
            f'"TERMINATE")\n'
            f'    touched.append(n)\n'
            f'_emit({{"terminated": touched}})\n')
        return _wrap(body)

    @staticmethod
    def wait_service_registration(name: str, timeout: float) -> str:
        """Block until the service row exists (the service job has started)
        and report its ports, or time out."""
        body = (
            f'deadline = time.time() + {timeout}\n'
            f'svc = None\n'
            f'while time.time() < deadline:\n'
            f'    svc = serve_state.get_service({name!r})\n'
            f'    if svc is not None:\n'
            f'        break\n'
            f'    time.sleep(0.5)\n'
            f'if svc is None:\n'
            f'    _emit({{"error": "service not registered in time"}})\n'
            f'else:\n'
            f'    _emit({{"controller_port": svc["controller_port"],\n'
            f'           "load_balancer_port": '
            f'svc["load_balancer_port"]}})\n')
        return _wrap(body)

    @staticmethod
    def update_service(name: str, spec_json: str, task_yaml: str,
                       mode: str = 'rolling') -> str:
        """POST the new spec to the service's controller API."""
        body = (
            f'import urllib.request\n'
            f'svc = serve_state.get_service({name!r})\n'
            f'if svc is None:\n'
            f'    _emit({{"error": "no such service"}})\n'
            f'else:\n'
            f'    req = urllib.request.Request(\n'
            f'        "http://127.0.0.1:%d/controller/update_service" '
            f'% svc["controller_port"],\n'
            f'        data=json.dumps({{"spec": {spec_json!r}, '
            f'"task_yaml": {task_yaml!r}, "mode": {mode!r}}}).encode(),\n'
            f'        headers={{"Content-Type": "application/json"}})\n'
            f'    with urllib.request.urlopen(req, timeout=10) as r:\n'
            f'        _emit(json.loads(r.read()))\n')
        return _wrap(body)

    @staticmethod
    def terminate_replica(name: str, replica_id: int, purge: bool) -> str:
        body = (
            f'import urllib.request\n'
            f'svc = serve_state.get_service({name!r})\n'
            f'if svc is None:\n'
            f'    _emit({{"error": "no such service"}})\n'
            f'else:\n'
            f'    req = urllib.request.Request(\n'
            f'        "http://127.0.0.1:%d/controller/terminate_replica" '
            f'% svc["controller_port"],\n'
            f'        data=json.dumps({{"replica_id": {replica_id}, '
            f'"purge": {purge!r}}}).encode(),\n'
            f'        headers={{"Content-Type": "application/json"}})\n'
            f'    with urllib.request.urlopen(req, timeout=10) as r:\n'
            f'        _emit(json.loads(r.read()))\n')
        return _wrap(body)

    @staticmethod
    def stream_replica_logs(name: str, replica_id: int,
                            follow: bool) -> str:
        """Stream a replica cluster's job logs through the controller."""
        body = (
            f'from skypilot_tpu import core\n'
            f'from skypilot_tpu.serve import replica_managers\n'
            f'cluster = replica_managers.replica_cluster_name('
            f'{name!r}, {replica_id})\n'
            f'sys.exit(core.tail_logs(cluster, follow={follow!r}))\n')
        return _wrap(body)


def format_service_table(services: List[Dict[str, Any]]) -> str:
    header = (f'{"NAME":<24}{"VERSION":<9}{"STATUS":<18}{"REPLICAS":<10}'
              f'{"ENDPOINT"}')
    lines = [header]
    for svc in services:
        ready = sum(1 for r in svc.get('replicas', [])
                    if r['status'] == 'READY')
        total = len(svc.get('replicas', []))
        lines.append(f'{svc["name"]:<24}{svc.get("version", 1):<9}'
                     f'{svc["status"]:<18}{f"{ready}/{total}":<10}'
                     f'{svc.get("endpoint") or "-"}')
    return '\n'.join(lines)
