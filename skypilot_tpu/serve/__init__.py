"""Serve plane: replica autoscaling + HTTP load balancing on TPU slices.

Parity: sky/serve/__init__.py — up/update/down/status/tail_logs/
terminate_replica + SkyTpuServiceSpec.
"""
from skypilot_tpu.serve.core import (controller_down, down, status,
                                     tail_logs, terminate_replica, up,
                                     update)
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec

__all__ = [
    'ReplicaStatus',
    'ServiceStatus',
    'SkyTpuServiceSpec',
    'controller_down',
    'down',
    'status',
    'tail_logs',
    'terminate_replica',
    'up',
    'update',
]
