"""Serve SDK: up / update / down / status / terminate_replica / tail_logs.

Parity: sky/serve/core.py — `up` (:95) validates the service YAML,
launches or reuses the per-user serve controller cluster, submits one
service job per service, and waits for the endpoint; the other calls are
RPC-by-codegen to the controller host.
"""
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import usage
from skypilot_tpu import exceptions, execution, logsys, state
from skypilot_tpu.backends import SliceBackend
from skypilot_tpu.serve import constants, serve_utils
from skypilot_tpu.serve.load_balancing_policies import DEFAULT_POLICY
from skypilot_tpu.serve.serve_utils import ServeCodeGen
from skypilot_tpu.serve.service_spec import SkyTpuServiceSpec
from skypilot_tpu.task import Task
from skypilot_tpu.utils import controller_utils, ux

logger = logsys.init_logger(__name__)


def _controller_handle(refresh: bool = False):
    name = controller_utils.controller_cluster_name(
        controller_utils.SERVE_CONTROLLER)
    if refresh:
        from skypilot_tpu import backend_utils
        record = backend_utils.refresh_cluster_record(name)
    else:
        record = state.get_cluster_from_name(name)
    return record['handle'] if record else None


def _head(required: bool = True):
    handle = _controller_handle()
    if handle is None:
        if required:
            raise exceptions.ClusterNotUpError(
                'No serve controller cluster found; is any service up?')
        return None
    return handle.head_runner()


def _dump_task_yaml(task: Task) -> str:
    import yaml
    fd, path = tempfile.mkstemp(prefix='skytpu-serve-', suffix='.yaml')
    os.close(fd)
    with open(path, 'w', encoding='utf-8') as f:
        yaml.safe_dump(task.to_yaml_config(), f, default_flow_style=False)
    return path


def _validate_service_task(task: Task) -> SkyTpuServiceSpec:
    if task.service is None:
        raise exceptions.InvalidTaskError(
            'Task must have a `service:` section for `serve.up`.')
    if task.run is None:
        raise exceptions.InvalidTaskError(
            'Service tasks require a run command.')
    return task.service


@usage.entrypoint('serve.up')
def up(task: Task,
       service_name: Optional[str] = None,
       *,
       policy: Optional[str] = None) -> Tuple[str, str]:
    """Bring a service up; returns (service_name, endpoint URL)."""
    spec = _validate_service_task(task)
    if policy is None:
        policy = spec.load_balancing_policy or DEFAULT_POLICY
    if service_name is None:
        service_name = serve_utils.generate_service_name(task.name)
    serve_utils.validate_service_name(service_name)
    # Duplicate-name check up front: the service job on the controller
    # would crash while wait_service_registration happily found the OLD
    # service's row and reported its endpoint as ours.
    if _controller_handle() is not None and any(
            s['name'] == service_name for s in status([service_name])):
        raise exceptions.ServeError(
            f'Service {service_name!r} already exists; use '
            f'serve.update() or pick another name.')

    local_yaml = _dump_task_yaml(task)
    remote_yaml = f'~/.skytpu/serve/tasks/{service_name}.yaml'
    task_resources = list(task.resources)
    controller_task = Task(
        name=f'serve-{service_name}',
        setup=controller_utils.controller_setup_commands(),
        run=(f'{controller_utils.CONTROLLER_ENV_PREFIX}'
             f'python3 -u -m skypilot_tpu.serve.service '
             f'--service-name {service_name} --task-yaml {remote_yaml} '
             f'--policy {policy}'),
        envs=_controller_envs(),
    )
    controller_task.set_file_mounts({
        remote_yaml: local_yaml,
        **controller_utils.credential_file_mounts(),
    })
    controller_task.set_resources(
        controller_utils.controller_resources(
            controller_utils.SERVE_CONTROLLER, task_resources))

    controller_name = controller_utils.controller_cluster_name(
        controller_utils.SERVE_CONTROLLER)
    logger.info('%s Launching service %r on controller %r.',
                ux.emph('[serve]'), service_name, controller_name)
    try:
        execution.launch(
            controller_task, cluster_name=controller_name,
            detach_run=True, stream_logs=False, fast=True,
            # Idle controllers stop themselves once every service is
            # gone (stop, not down: the serve state DB survives).
            # Parity: sky/serve/core.py:202-208.
            idle_minutes_to_autostop=(
                controller_utils.controller_autostop_minutes(
                    controller_utils.SERVE_CONTROLLER)))
    finally:
        os.remove(local_yaml)

    # Wait for the service process to register itself, then report the
    # endpoint (controller head IP + LB port).
    handle = _controller_handle()
    head = handle.head_runner()
    cmd = ServeCodeGen.wait_service_registration(
        service_name, constants.up_wait_timeout())
    rc, stdout, stderr = head.run(cmd, require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(rc, 'serve up', stderr[-800:])
    result = serve_utils.parse_result(stdout)
    if 'error' in result:
        raise exceptions.ServeError(
            f'Service {service_name!r} failed to start: {result["error"]}. '
            f'Check `serve.tail_logs({service_name!r})`.')
    info = handle.cluster_info()
    ip = info.head.external_ip or info.head.internal_ip
    endpoint = f'http://{ip}:{result["load_balancer_port"]}'
    logger.info('%s Service %r registered; endpoint: %s', ux.ok('[serve]'),
                service_name, endpoint)
    return service_name, endpoint


def _controller_envs() -> Dict[str, str]:
    # SKYTPU_SERVE_*: serve-plane loop intervals and QoS knobs.
    # SKYTPU_LB_*: control-plane resilience knobs (journal path, hedge
    # deadline, retry budget, probation) — the LB runs on the controller
    # host, so they must ride along too.
    envs = {}
    for key in os.environ:
        if key.startswith(('SKYTPU_SERVE_', 'SKYTPU_LB_')):
            envs[key] = os.environ[key]
    return envs


@usage.entrypoint('serve.update')
def update(task: Task, service_name: str,
           mode: str = 'rolling') -> int:
    """Update to a new task/spec; returns the new version.

    mode: 'rolling' (bounded surge of one, default) or 'blue_green'
    (full new fleet reaches READY before any old replica drains).
    Parity: sky/serve/core.py:309 UpdateMode.
    """
    mode = serve_utils.UpdateMode(mode).value   # validate early
    spec = _validate_service_task(task)
    local_yaml = _dump_task_yaml(task)
    remote_yaml = (f'~/.skytpu/serve/tasks/{service_name}-'
                   f'v{int(time.time())}.yaml')  # det-ok: filename stamp
    handle = _controller_handle()
    if handle is None:
        raise exceptions.ClusterNotUpError(
            'No serve controller cluster found.')
    head = handle.head_runner()
    try:
        head.rsync(local_yaml, remote_yaml, up=True)
    finally:
        os.remove(local_yaml)
    cmd = ServeCodeGen.update_service(service_name, spec.to_json(),
                                      remote_yaml, mode=mode)
    rc, stdout, stderr = head.run(cmd, require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(rc, 'serve update', stderr[-800:])
    result = serve_utils.parse_result(stdout)
    if 'error' in result:
        raise exceptions.ServeError(result['error'])
    logger.info('%s Service %r updating to version %d.', ux.ok('[serve]'),
                service_name, result['version'])
    return result['version']


def status(service_names: Optional[List[str]] = None
           ) -> List[Dict[str, Any]]:
    """Service records (with replica details and endpoint)."""
    head = _head(required=False)
    if head is None:
        return []
    rc, stdout, stderr = head.run(ServeCodeGen.get_service_status(),
                                  require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(rc, 'serve status', stderr[-800:])
    services = serve_utils.parse_result(stdout)
    handle = _controller_handle()
    info = handle.cluster_info()
    ip = info.head.external_ip or info.head.internal_ip
    for svc in services:
        svc['endpoint'] = f'http://{ip}:{svc["load_balancer_port"]}'
    if service_names is not None:
        services = [s for s in services if s['name'] in service_names]
    return services


@usage.entrypoint('serve.down')
def down(service_names: Optional[List[str]] = None,
         all_services: bool = False,
         purge: bool = False) -> List[str]:
    """Terminate services (their replicas tear down asynchronously)."""
    if service_names is None and not all_services:
        raise ValueError('Specify service_names or all_services=True.')
    head = _head()
    cmd = ServeCodeGen.terminate_services(
        None if all_services else service_names, purge=purge)
    rc, stdout, stderr = head.run(cmd, require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(rc, 'serve down', stderr[-800:])
    terminated = serve_utils.parse_result(stdout)['terminated']
    logger.info('%s Terminating service(s): %s', ux.emph('[serve]'),
                ', '.join(terminated) or '(none)')
    return terminated


def terminate_replica(service_name: str, replica_id: int,
                      purge: bool = False) -> None:
    head = _head()
    cmd = ServeCodeGen.terminate_replica(service_name, replica_id, purge)
    rc, stdout, stderr = head.run(cmd, require_outputs=True)
    if rc != 0:
        raise exceptions.CommandError(rc, 'serve terminate-replica',
                                      stderr[-800:])
    result = serve_utils.parse_result(stdout)
    if 'error' in result:
        raise exceptions.ServeError(result['error'])


def tail_logs(service_name: str,
              *,
              target: str = 'controller',
              replica_id: Optional[int] = None,
              follow: bool = True) -> int:
    """Stream logs: the service process ('controller') or one replica."""
    handle = _controller_handle()
    if handle is None:
        raise exceptions.ClusterNotUpError(
            'No serve controller cluster found.')
    head = handle.head_runner()
    if replica_id is not None or target == 'replica':
        if replica_id is None:
            raise ValueError('replica target needs replica_id')
        cmd = ServeCodeGen.stream_replica_logs(service_name, replica_id,
                                               follow)
        return int(head.run(cmd, stream_logs=True, log_path='/dev/null'))
    # Controller/LB logs = the service job's log on the controller cluster.
    from skypilot_tpu import core as core_lib
    jobs = core_lib.queue(
        controller_utils.controller_cluster_name(
            controller_utils.SERVE_CONTROLLER))
    for job in jobs:
        if job.get('job_name') == f'serve-{service_name}':
            return core_lib.tail_logs(
                controller_utils.controller_cluster_name(
                    controller_utils.SERVE_CONTROLLER),
                job_id=job['job_id'], follow=follow)
    raise exceptions.ServeError(
        f'No service job found for {service_name!r}.')


def controller_down(purge: bool = False) -> None:
    """Tear down the per-user serve controller cluster."""
    name = controller_utils.controller_cluster_name(
        controller_utils.SERVE_CONTROLLER)
    record = state.get_cluster_from_name(name)
    if record is None:
        return
    SliceBackend().teardown(record['handle'], terminate=True, purge=purge)
