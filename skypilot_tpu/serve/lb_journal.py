"""Append-compacted JSON journal for load-balancer warm restart.

The LB's slow-moving state — circuit-breaker machines + backoff clocks,
the prefix-affinity ``_seen`` map, per-replica tp/latency snapshots,
tenant token-bucket levels, the retry-budget level — lives in memory
and dies with the process.  This journal makes an LB restart *warm*:
the revived process re-adopts breaker backoffs (a known-bad replica
stays ejected across the restart) and affinity residency (cache-aware
routing resumes without re-learning the fleet) instead of starting
blind.

Design: a key->doc map persisted as an append-only log of one-line
JSON records ``{"k": <key>, "v": <doc>}``.  Appends are cheap (one
line + flush); every ``compact_every`` appends the file is rewritten
to one line per live key via a temp file + ``os.replace`` (atomic on
POSIX), so the journal stays small and a crash mid-compaction leaves
the previous complete file.  ``fsync=True`` (used only on breaker
transitions — the rare, high-value edges) forces the line to disk;
routine soft-state writes ride the OS page cache, which is the right
trade: losing two seconds of latency EWMA is free, losing an OPEN
breaker means one bad request after restart.

Loading tolerates a truncated tail (torn final line from a crash
mid-append): complete lines win, the torn line is dropped.

Determinism: the clock is injected (DET scope covers serve/); nothing
here reads the wall clock.  Age is *this-process* age — seconds since
the last put() by this process via the injected monotonic clock — and
is None before the first write, because monotonic readings are not
comparable across processes.
"""
import json
import os
import threading
from typing import Any, Callable, Dict, Optional

from skypilot_tpu.analysis import sanitizers


class LBJournal:

    def __init__(self, path: str, clock: Callable[[], float],
                 compact_every: int = 256) -> None:
        assert clock is not None, 'inject the LB clock seam'
        self.path = os.path.expanduser(path)
        self._clock = clock
        self._compact_every = max(1, int(compact_every))
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.lb_journal._lock')
        self._state: Dict[str, Any] = {}  # guarded-by: _lock
        self._appends = 0  # guarded-by: _lock (since last compaction)
        self._last_put: Optional[float] = None  # guarded-by: _lock
        self._fh = None  # guarded-by: _lock (append handle, lazy)
        # True when the existing file ends mid-line (crash mid-append):
        # the first append must start on a fresh line or it would fuse
        # with the torn tail and corrupt BOTH records.
        self._needs_newline = False  # guarded-by: _lock
        os.makedirs(os.path.dirname(self.path) or '.', exist_ok=True)
        self._load()

    # --------------------------------------------------------------- load

    def _load(self) -> None:
        """Replay the log; later lines win.  A torn final line (crash
        mid-append) is dropped silently — everything before it is a
        complete record."""
        if not os.path.exists(self.path):
            return
        with self._lock:   # constructor-only caller; lock for the record
            with open(self.path, 'rb') as fb:
                fb.seek(0, os.SEEK_END)
                if fb.tell() > 0:
                    fb.seek(-1, os.SEEK_END)
                    self._needs_newline = fb.read(1) != b'\n'
            with open(self.path, encoding='utf-8') as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail / corrupt line: skip
                    if isinstance(rec, dict) and 'k' in rec:
                        self._state[str(rec['k'])] = rec.get('v')

    # -------------------------------------------------------------- write

    def put(self, key: str, doc: Any, fsync: bool = False) -> None:
        """Record `key` -> `doc` (any JSON-serialisable value).  With
        fsync=True the line is forced to disk before returning — reserve
        that for breaker transitions; soft state should not eat an
        fsync per probe round."""
        line = json.dumps({'k': key, 'v': doc}, sort_keys=True)
        with self._lock:
            self._state[key] = doc
            if self._fh is None:
                self._fh = open(self.path, 'a', encoding='utf-8')
                if self._needs_newline:
                    self._fh.write('\n')
                    self._needs_newline = False
            self._fh.write(line + '\n')
            self._fh.flush()
            if fsync:
                os.fsync(self._fh.fileno())
            self._last_put = self._clock()
            self._appends += 1
            if self._appends >= self._compact_every:
                self._compact()

    def _compact(self) -> None:  # locked: _lock
        """Rewrite to one line per live key, atomically (temp file +
        os.replace): a crash mid-compaction leaves the old file."""
        tmp = self.path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            for key in sorted(self._state):
                f.write(json.dumps({'k': key, 'v': self._state[key]},
                                   sort_keys=True) + '\n')
            f.flush()
            os.fsync(f.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        self._appends = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # --------------------------------------------------------------- read

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._state.get(key, default)

    def snapshot(self) -> Dict[str, Any]:
        """Deep-ish copy of the full key->doc map (one json round-trip:
        callers may mutate freely)."""
        with self._lock:
            return json.loads(json.dumps(self._state))

    def age_s(self) -> Optional[float]:
        """Seconds since the last put() BY THIS PROCESS (injected
        monotonic clock); None before the first write.  Not comparable
        across restarts — a freshly revived LB reports None until its
        first journal write."""
        with self._lock:
            if self._last_put is None:
                return None
            return max(0.0, self._clock() - self._last_put)
