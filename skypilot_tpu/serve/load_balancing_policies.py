"""Load-balancing policies.

Parity: sky/serve/load_balancing_policies.py:22,47 — pluggable policy with
a ready-replica set pushed from the controller sync; we also ship a
least-outstanding-requests policy (the reference only has round-robin)
and a prefix-affinity policy that makes N replicas approximate ONE
logical radix cache (see :class:`PrefixAffinityPolicy`).
"""
import bisect
import hashlib
import threading
from dataclasses import dataclass
from collections import OrderedDict
from typing import Dict, List, Optional
from typing import Collection

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.serve import constants


@dataclass
class RequestContext:
    """What the LB knows about a request at routing time.

    ``tokens``: the native /generate token prompt when present (None
    for text prompts and passthrough traffic — affinity policies fall
    back to load-only selection).  ``adapter``: the LoRA adapter the
    request names; prefix KV is adapter-dependent, so the route key
    includes it exactly like ``infer/radix.py``'s per-adapter roots.
    """
    tokens: Optional[List[int]] = None
    adapter: Optional[str] = None


def _h64(data: bytes) -> int:
    """Stable 64-bit hash (ring points + route keys).  blake2b, not
    hash(): Python's string hashing is salted per-process and the ring
    layout must be identical across LB restarts."""
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          'big')


class LoadBalancingPolicy:
    """Tracks ready replicas and picks one per request."""

    NAME = 'base'
    _REGISTRY: Dict[str, type] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        LoadBalancingPolicy._REGISTRY[cls.NAME] = cls

    @classmethod
    def make(cls, name: str) -> 'LoadBalancingPolicy':
        try:
            return cls._REGISTRY[name]()
        except KeyError:
            raise ValueError(
                f'Unknown load balancing policy {name!r}; '
                f'available: {sorted(cls._REGISTRY)}') from None

    def __init__(self):
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.lb_policy._lock')
        self.ready_replicas: List[str] = []  # guarded-by: _lock

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if set(replicas) != set(self.ready_replicas):
                self._on_replica_change(replicas)
            self.ready_replicas = list(replicas)

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        pass

    def select_replica(self,
                       exclude: Collection[str] = (),
                       context: Optional[RequestContext] = None
                       ) -> Optional[str]:
        """Pick a ready replica not in ``exclude``.

        ``exclude`` carries the LB's per-request no-go set: replicas
        already tried this request, replicas whose circuit breaker is
        open, and draining replicas.  None = every ready replica is
        excluded (or none are ready).  ``context`` carries what the LB
        parsed out of the request body (token prompt, adapter) —
        affinity-aware policies route on it, the others ignore it.
        """
        raise NotImplementedError

    def request_done(self, replica: str) -> None:
        """Called when a proxied request finishes (success or not)."""

    def observe_replica(self, replica: str, health_doc: dict) -> None:
        """Probe-thread feed: the replica's parsed /healthz document
        (which carries the engine's kv/radix counters).  Default: no-op.
        """

    def stats(self) -> dict:
        """Policy-specific counters for GET /lb/stats."""
        return {'name': self.NAME}

    def export_seen(self) -> Optional[dict]:
        """Warm-restart journal export of the policy's slow-moving
        routing state; None (the default) = nothing to journal."""
        return None

    def import_seen(self, doc: dict) -> None:
        """Re-adopt a prior export_seen() doc after an LB restart.
        Default: no-op."""


class RoundRobinPolicy(LoadBalancingPolicy):
    """Parity: sky/serve/load_balancing_policies.py:47."""

    NAME = 'round_robin'

    def __init__(self):
        super().__init__()
        self._index = 0  # guarded-by: _lock

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        self._index = 0

    def select_replica(self,
                       exclude: Collection[str] = (),
                       context: Optional[RequestContext] = None
                       ) -> Optional[str]:
        with self._lock:
            if not self.ready_replicas:
                return None
            # One full lap at most: skip excluded replicas instead of
            # returning them (the retry loop would otherwise see an
            # already-tried replica and give up with untried ones left).
            for _ in range(len(self.ready_replicas)):
                replica = self.ready_replicas[self._index %
                                              len(self.ready_replicas)]
                self._index += 1
                if replica not in exclude:
                    return replica
            return None


class LeastLoadPolicy(LoadBalancingPolicy):
    """Pick the replica with the fewest outstanding proxied requests."""

    NAME = 'least_load'

    def __init__(self):
        super().__init__()
        self._outstanding: Dict[str, int] = {}  # guarded-by: _lock

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        self._outstanding = {
            r: self._outstanding.get(r, 0) for r in replicas
        }

    def select_replica(self,
                       exclude: Collection[str] = (),
                       context: Optional[RequestContext] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = [r for r in self.ready_replicas
                          if r not in exclude]
            if not candidates:
                return None
            replica = min(candidates,
                          key=lambda r: self._outstanding.get(r, 0))
            self._outstanding[replica] = (
                self._outstanding.get(replica, 0) + 1)
            return replica

    def request_done(self, replica: str) -> None:
        with self._lock:
            if replica in self._outstanding:
                self._outstanding[replica] = max(
                    0, self._outstanding[replica] - 1)


class PrefixAffinityPolicy(LeastLoadPolicy):
    """Route so the replica fleet approximates ONE logical radix cache.

    Each replica grows a private radix tree (``infer/radix.py``) keyed
    on ``kv_block_size``-token runs per adapter; blind balancing makes
    a prefix hot on replica A a cold full-prefill on replica B, so the
    fleet hit rate decays like 1/N.  This policy routes by the SAME
    key the tree uses:

    - **Route key** — a chain hash over the prompt's leading
      block-aligned token runs under the request's adapter, capped at
      ``affinity_route_blocks`` runs, so every prompt sharing that
      lead lands on the same replica.
    - **Consistent hashing** — the key is looked up on a vnode ring
      over the ready set, so replica join/leave/eject moves only
      ~1/N of the key space (the other replicas' warm prefixes stay
      put).
    - **Bounded load** — the ring owner is used only while its
      outstanding count stays under
      ``factor * mean_outstanding + slack`` (consistent hashing with
      bounded loads); the factor grows with the fleet's observed radix
      hit rate (affinity is worth more imbalance when it's paying off)
      and a replica whose KV pool occupancy is near-full carries a
      load penalty (new prefixes would thrash its tree).  Both signals
      arrive through the LB's /healthz probe (``observe_replica``).
    - **Spill + failover** — when the owner is excluded (dead breaker,
      draining, already tried this request) or over the bound, the
      pick prefers the candidate with the LONGEST recorded cached
      prefix for this prompt (ring order, then load, break ties), so a
      mid-stream failover resume re-prefills only the suffix on the
      warmest survivor.

    Residency is tracked optimistically at select time: routing a
    prompt to a replica is what populates that replica's radix tree,
    so the per-depth chain-hash map is the LB-side shadow of the
    fleet's trees (bounded LRU; it is a hint, never a correctness
    input — greedy output is replica-independent).
    """

    NAME = 'prefix_affinity'

    _SEEN_CAP = 4096             # tracked (prefix-depth, holders) entries

    def __init__(self):
        super().__init__()
        self._vnodes = max(1, constants.affinity_vnodes())
        self._route_blocks = max(1, constants.affinity_route_blocks())
        self._track_blocks = max(self._route_blocks,
                                 constants.affinity_track_blocks())
        self._load_factor = constants.affinity_load_factor()
        self._load_slack = constants.affinity_load_slack()
        self._hit_rate_weight = constants.affinity_hit_rate_weight()
        self._occ_high = constants.affinity_occupancy_high()
        self._occ_penalty = constants.affinity_occupancy_penalty()
        self._block_size = max(1, constants.affinity_block_size())  # guarded-by: _lock
        self._ring: List[int] = []          # guarded-by: _lock
        self._ring_urls: List[str] = []     # guarded-by: _lock
        self._kv: Dict[str, dict] = {}      # guarded-by: _lock
        # chain-hash -> {replica: last-route tick}; LRU-bounded.
        self._seen: 'OrderedDict[int, Dict[str, int]]' = OrderedDict()  # guarded-by: _lock
        self._tick = 0                      # guarded-by: _lock
        self._affinity: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        self._keyed = 0                     # guarded-by: _lock
        self._blind = 0                     # guarded-by: _lock

    # ------------------------------------------------------------- ring

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        super()._on_replica_change(replicas)
        points = []
        for url in replicas:
            for v in range(self._vnodes):
                points.append((_h64(f'{url}#{v}'.encode()), url))
        points.sort()
        self._ring = [p for p, _ in points]
        self._ring_urls = [u for _, u in points]
        self._affinity = {
            u: self._affinity.get(u, {'hits': 0, 'spills': 0})
            for u in replicas
        }

    def _ring_owner(self, key: int) -> Optional[str]:  # locked: _lock
        if not self._ring:
            return None
        i = bisect.bisect_right(self._ring, key) % len(self._ring)
        return self._ring_urls[i]

    def _ring_order(self, key: int) -> Dict[str, int]:  # locked: _lock
        """url -> position walking clockwise from ``key`` (owner = 0)."""
        order: Dict[str, int] = {}
        n = len(self._ring)
        if not n:
            return order
        start = bisect.bisect_right(self._ring, key)
        for step in range(n):
            url = self._ring_urls[(start + step) % n]
            if url not in order:
                order[url] = len(order)
        return order

    # ------------------------------------------------------------- keys

    def _context_chain(self, context: Optional[RequestContext]
                       ) -> List[int]:  # locked: _lock
        """Chain hashes of the prompt's leading block runs (depth i's
        hash covers runs 0..i), capped at the tracking depth.  Empty
        when the request carries no usable token prompt."""
        if context is None or not context.tokens:
            return []
        bs = self._block_size
        tokens = context.tokens
        depth = min(len(tokens) // bs, self._track_blocks)
        if depth < 1:
            return []
        h = _h64(repr(context.adapter).encode())
        chain = []
        try:
            for i in range(depth):
                run = ','.join(
                    str(int(t)) for t in tokens[i * bs:(i + 1) * bs])
                h = _h64(h.to_bytes(8, 'big') + run.encode())
                chain.append(h)
        except (TypeError, ValueError):
            return []           # non-integer tokens: route blind
        return chain

    def _route_key(self, chain: List[int]) -> int:  # locked: _lock
        return chain[min(len(chain), self._route_blocks) - 1]

    def owner_of(self, context: Optional[RequestContext]
                 ) -> Optional[str]:
        """The ring owner for a context among the current ready set —
        pure introspection (no load input, no counter side effects) for
        tests and operators."""
        with self._lock:
            chain = self._context_chain(context)
            if not chain:
                return None
            return self._ring_owner(self._route_key(chain))

    # ----------------------------------------------------------- load

    def _eff_load(self, url: str) -> float:  # locked: _lock
        occ = (self._kv.get(url) or {}).get('occupancy')
        penalty = (self._occ_penalty
                   if isinstance(occ, (int, float)) and
                   occ >= self._occ_high else 0.0)
        return self._outstanding.get(url, 0) + penalty

    def _load_bound(self, candidates: List[str],
                    url: str) -> float:  # locked: _lock
        """Bounded-load cap for routing to ``url`` among
        ``candidates``.  Classic bounded loads assume a uniform fleet;
        ours is mixed (TP vs DP replicas), so each replica's share of
        the total is weighted by its probed tensor-parallel degree —
        a tp=2 replica serves decode faster than a tp=1 replica and
        must not be capped at the tp=1 share.  Equal degrees
        degenerate to the uniform 1/N bound."""
        total = sum(self._outstanding.get(c, 0) for c in candidates)
        rates = []
        for c in candidates:
            radix = (self._kv.get(c) or {}).get('radix')
            if isinstance(radix, dict) and \
                    isinstance(radix.get('hit_rate'), (int, float)):
                rates.append(float(radix['hit_rate']))
        fleet_hit = sum(rates) / len(rates) if rates else 0.0
        factor = self._load_factor + self._hit_rate_weight * fleet_hit
        tps: Dict[str, float] = {}
        for c in candidates:
            tp = (self._kv.get(c) or {}).get('tp')
            tps[c] = float(tp) if isinstance(tp, int) and tp > 0 else 1.0
        share = tps.get(url, 1.0) / sum(tps.values())
        return factor * (total + 1) * share + self._load_slack

    # ------------------------------------------------------- residency

    def _seen_depth(self, chain: List[int], url: str) -> int:  # locked: _lock
        depth = 0
        for i, h in enumerate(chain):
            holders = self._seen.get(h)
            if holders is None or url not in holders:
                break
            depth = i + 1
        return depth

    def _record_seen(self, chain: List[int], url: str) -> None:  # locked: _lock
        self._tick += 1
        for h in chain:
            holders = self._seen.get(h)
            if holders is None:
                holders = self._seen[h] = {}
            else:
                self._seen.move_to_end(h)
            holders[url] = self._tick
        while len(self._seen) > self._SEEN_CAP:
            self._seen.popitem(last=False)

    # ------------------------------------------------------- selection

    def select_replica(self,
                       exclude: Collection[str] = (),
                       context: Optional[RequestContext] = None
                       ) -> Optional[str]:
        with self._lock:
            candidates = [r for r in self.ready_replicas
                          if r not in exclude]
            if not candidates:
                return None
            chain = self._context_chain(context)
            if not chain:
                # No token prompt to key on: plain least-load (with the
                # occupancy penalty, so blind traffic also avoids
                # cache-full replicas).
                self._blind += 1
                chosen = min(candidates, key=self._eff_load)
                self._outstanding[chosen] = (
                    self._outstanding.get(chosen, 0) + 1)
                return chosen
            self._keyed += 1
            key = self._route_key(chain)
            owner = self._ring_owner(key)
            if owner is not None and owner not in exclude and \
                    self._eff_load(owner) < \
                    self._load_bound(candidates, owner):
                chosen = owner
            else:
                # Owner dead/draining/tried or over the bound: prefer
                # the survivor holding the LONGEST cached prefix for
                # this prompt (failover resumes re-prefill only the
                # suffix there), then ring order (deterministic spill
                # target), then load.
                order = self._ring_order(key)
                ranked = sorted(
                    candidates,
                    key=lambda u: (-self._seen_depth(chain, u),
                                   order.get(u, len(order)),
                                   self._eff_load(u)))
                under = [u for u in ranked if self._eff_load(u) <
                         self._load_bound(candidates, u)]
                chosen = under[0] if under else min(
                    candidates, key=self._eff_load)
            self._record_seen(chain, chosen)
            counters = self._affinity.setdefault(
                chosen, {'hits': 0, 'spills': 0})
            counters['hits' if chosen == owner else 'spills'] += 1
            self._outstanding[chosen] = (
                self._outstanding.get(chosen, 0) + 1)
            return chosen

    # ----------------------------------------------------- health feed

    def observe_replica(self, replica: str, health_doc: dict) -> None:
        kv = health_doc.get('kv') if isinstance(health_doc, dict) else None
        if not isinstance(kv, dict):
            return
        with self._lock:
            bs = kv.get('block_size')
            if isinstance(bs, int) and bs > 0 and bs != self._block_size:
                # The fleet's real block size: route keys hashed under
                # the old run length no longer match anything.
                self._block_size = bs
                self._seen.clear()
            self._kv[replica] = kv

    # ------------------------------------------------ journal (PR 18)

    def export_seen(self) -> Optional[dict]:
        """The residency shadow map + tick, JSON-shaped: chain hashes
        become decimal strings (JSON object keys are strings).  This is
        the state an LB restart cannot re-learn quickly — losing it
        costs one full cold pass of prefix re-prefills fleet-wide."""
        with self._lock:
            return {
                'tick': self._tick,
                'block_size': self._block_size,
                'seen': {str(h): dict(holders)
                         for h, holders in self._seen.items()},
            }

    def import_seen(self, doc: dict) -> None:
        """Re-adopt an export_seen() doc.  Residency is a hint, never a
        correctness input, so a stale entry is harmless (worst case:
        one spill picks a colder survivor)."""
        if not isinstance(doc, dict):
            return
        with self._lock:
            self._tick = max(self._tick, int(doc.get('tick', 0)))
            bs = doc.get('block_size')
            if isinstance(bs, int) and bs > 0:
                self._block_size = bs
            for key, holders in (doc.get('seen') or {}).items():
                try:
                    h = int(key)
                except (TypeError, ValueError):
                    continue
                if isinstance(holders, dict):
                    self._seen[h] = {str(u): int(t)
                                     for u, t in holders.items()}
            while len(self._seen) > self._SEEN_CAP:
                self._seen.popitem(last=False)

    def stats(self) -> dict:
        with self._lock:
            return {
                'name': self.NAME,
                'keyed': self._keyed,
                'blind': self._blind,
                'affinity_hits': sum(c['hits']
                                     for c in self._affinity.values()),
                'affinity_spills': sum(c['spills']
                                       for c in self._affinity.values()),
                'per_replica': {u: dict(c)
                                for u, c in self._affinity.items()},
                'tracked_prefixes': len(self._seen),
                'block_size': self._block_size,
            }


DEFAULT_POLICY = RoundRobinPolicy.NAME
