"""Load-balancing policies.

Parity: sky/serve/load_balancing_policies.py:22,47 — pluggable policy with
a ready-replica set pushed from the controller sync; we also ship a
least-outstanding-requests policy (the reference only has round-robin).
"""
import threading
from typing import Dict, List, Optional
from typing import Collection

from skypilot_tpu.analysis import sanitizers


class LoadBalancingPolicy:
    """Tracks ready replicas and picks one per request."""

    NAME = 'base'
    _REGISTRY: Dict[str, type] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        LoadBalancingPolicy._REGISTRY[cls.NAME] = cls

    @classmethod
    def make(cls, name: str) -> 'LoadBalancingPolicy':
        try:
            return cls._REGISTRY[name]()
        except KeyError:
            raise ValueError(
                f'Unknown load balancing policy {name!r}; '
                f'available: {sorted(cls._REGISTRY)}') from None

    def __init__(self):
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'serve.lb_policy._lock')
        self.ready_replicas: List[str] = []  # guarded-by: _lock

    def set_ready_replicas(self, replicas: List[str]) -> None:
        with self._lock:
            if set(replicas) != set(self.ready_replicas):
                self._on_replica_change(replicas)
            self.ready_replicas = list(replicas)

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        pass

    def select_replica(self,
                       exclude: Collection[str] = ()) -> Optional[str]:
        """Pick a ready replica not in ``exclude``.

        ``exclude`` carries the LB's per-request no-go set: replicas
        already tried this request, replicas whose circuit breaker is
        open, and draining replicas.  None = every ready replica is
        excluded (or none are ready)."""
        raise NotImplementedError

    def request_done(self, replica: str) -> None:
        """Called when a proxied request finishes (success or not)."""


class RoundRobinPolicy(LoadBalancingPolicy):
    """Parity: sky/serve/load_balancing_policies.py:47."""

    NAME = 'round_robin'

    def __init__(self):
        super().__init__()
        self._index = 0  # guarded-by: _lock

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        self._index = 0

    def select_replica(self,
                       exclude: Collection[str] = ()) -> Optional[str]:
        with self._lock:
            if not self.ready_replicas:
                return None
            # One full lap at most: skip excluded replicas instead of
            # returning them (the retry loop would otherwise see an
            # already-tried replica and give up with untried ones left).
            for _ in range(len(self.ready_replicas)):
                replica = self.ready_replicas[self._index %
                                              len(self.ready_replicas)]
                self._index += 1
                if replica not in exclude:
                    return replica
            return None


class LeastLoadPolicy(LoadBalancingPolicy):
    """Pick the replica with the fewest outstanding proxied requests."""

    NAME = 'least_load'

    def __init__(self):
        super().__init__()
        self._outstanding: Dict[str, int] = {}  # guarded-by: _lock

    def _on_replica_change(self, replicas: List[str]) -> None:  # locked: _lock
        self._outstanding = {
            r: self._outstanding.get(r, 0) for r in replicas
        }

    def select_replica(self,
                       exclude: Collection[str] = ()) -> Optional[str]:
        with self._lock:
            candidates = [r for r in self.ready_replicas
                          if r not in exclude]
            if not candidates:
                return None
            replica = min(candidates,
                          key=lambda r: self._outstanding.get(r, 0))
            self._outstanding[replica] = (
                self._outstanding.get(replica, 0) + 1)
            return replica

    def request_done(self, replica: str) -> None:
        with self._lock:
            if replica in self._outstanding:
                self._outstanding[replica] = max(
                    0, self._outstanding[replica] - 1)


DEFAULT_POLICY = RoundRobinPolicy.NAME
