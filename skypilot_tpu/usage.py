"""Usage telemetry: schema-ed per-invocation records, local-first.

Parity: sky/usage/usage_lib.py — every public entrypoint records one
message (command, resources, per-stage durations, exception class) — with
one deliberate change: records spool to a local JSONL file
($SKYTPU_HOME/usage/usage.jsonl) and are only POSTed when an endpoint is
explicitly configured (SKYTPU_USAGE_ENDPOINT); the reference ships to a
hardcoded Loki (usage_lib.py:296).  Opt out entirely with
SKYTPU_DISABLE_USAGE_COLLECTION=1.

Never raises: telemetry failure must not fail user work.
"""
import contextlib
import functools
import json
import os
import threading
import time
import traceback
import uuid
from typing import Any, Dict, Optional

_DISABLE_ENV = 'SKYTPU_DISABLE_USAGE_COLLECTION'
_ENDPOINT_ENV = 'SKYTPU_USAGE_ENDPOINT'
_RUN_ID = str(uuid.uuid4())[:8]

_local = threading.local()


def disabled() -> bool:
    return os.environ.get(_DISABLE_ENV, '0') == '1'


def _spool_path() -> str:
    from skypilot_tpu.utils import common
    d = os.path.join(common.home_dir(), 'usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'usage.jsonl')


class _Message:
    """One entrypoint invocation's record, built up as stages run."""

    def __init__(self, entrypoint: str):
        self.entrypoint = entrypoint
        self.start = time.time()
        self.stages: Dict[str, float] = {}
        self.fields: Dict[str, Any] = {}
        self.exception: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            'schema_version': 1,
            'run_id': _RUN_ID,
            'entrypoint': self.entrypoint,
            'start_time': self.start,
            'duration_s': round(time.time() - self.start, 3),
            'stages': {k: round(v, 3) for k, v in self.stages.items()},
            'exception': self.exception,
            **self.fields,
        }


def current() -> Optional[_Message]:
    return getattr(_local, 'message', None)


def record(key: str, value: Any) -> None:
    """Attach a field (e.g. resources str, cluster name) to the active
    entrypoint's record.  No-op when no entrypoint is active."""
    msg = current()
    if msg is not None:
        try:
            json.dumps(value)
            msg.fields[key] = value
        except (TypeError, ValueError):
            msg.fields[key] = str(value)


@contextlib.contextmanager
def stage(name: str):
    """Time one stage of the active entrypoint."""
    t0 = time.time()
    try:
        yield
    finally:
        msg = current()
        if msg is not None:
            msg.stages[name] = msg.stages.get(name, 0.0) + time.time() - t0


_SPOOL_MAX_BYTES = 16 * 1024 * 1024


def _flush(msg: _Message) -> None:
    payload = msg.to_dict()
    try:
        path = _spool_path()
        try:
            if os.path.getsize(path) > _SPOOL_MAX_BYTES:
                os.replace(path, path + '.1')  # keep one rotated generation
        except OSError:
            pass
        with open(path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(payload) + '\n')
    except OSError:
        return
    endpoint = os.environ.get(_ENDPOINT_ENV)
    if endpoint:
        # Fire-and-forget: a slow/unreachable endpoint must not add
        # latency to the exit path of every command (parity: the
        # reference posts from a thread for the same reason).
        threading.Thread(target=_post, args=(endpoint, payload),
                         daemon=True).start()


def _post(endpoint: str, payload: Dict[str, Any]) -> None:
    try:
        import urllib.request
        req = urllib.request.Request(
            endpoint, data=json.dumps(payload).encode(),
            headers={'Content-Type': 'application/json'})
        urllib.request.urlopen(req, timeout=2)
    except Exception:  # pylint: disable=broad-except
        pass  # best-effort; never fail user work over telemetry


def entrypoint(name_or_fn):
    """Decorator recording one usage message per outermost invocation.
    Parity: @usage_lib.entrypoint (sky/usage/usage_lib.py:447)."""

    def _wrap(fn, name):

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if disabled() or current() is not None:  # nested: outer records
                return fn(*args, **kwargs)
            msg = _Message(name)
            _local.message = msg
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                msg.exception = type(e).__name__
                msg.fields.setdefault(
                    'exception_site',
                    traceback.extract_tb(e.__traceback__)[-1].name
                    if e.__traceback__ else None)
                raise
            finally:
                _local.message = None
                _flush(msg)

        return wrapper

    if callable(name_or_fn):
        return _wrap(name_or_fn, name_or_fn.__qualname__)
    return lambda fn: _wrap(fn, name_or_fn)
