"""Log tailing on the head host (merged multi-host job logs).

Parity: sky/skylet/log_lib.py:381 (tail_logs with follow) — simplified: the
driver already fans logs into one run.log per job, so tailing is a single
file follow keyed by job status.
"""
import os
import time
from typing import Iterator, Optional

from skypilot_tpu.podlet import job_lib

_FOLLOW_POLL_SECONDS = 0.2


def _log_path(job: dict) -> str:
    return os.path.join(job_lib.log_dir(job['run_timestamp']), 'run.log')


def tail_logs(job_id: Optional[int] = None, follow: bool = True,
              lines_from_end: Optional[int] = None) -> Iterator[str]:
    """Yield log lines; with follow=True, stream until the job ends."""
    if job_id is None:
        job_id = job_lib.get_latest_job_id()
        if job_id is None:
            yield '(no jobs submitted yet)\n'
            return
    job = job_lib.get_job(job_id)
    if job is None:
        yield f'(job {job_id} not found)\n'
        return
    path = _log_path(job)
    # Wait for the driver to create the log file.
    waited = 0.0
    while not os.path.exists(path):
        job = job_lib.get_job(job_id)
        if job['status'].is_terminal() or not follow or waited > 30:
            if os.path.exists(path):
                break
            yield f'(no logs for job {job_id}; status: '\
                f'{job["status"].value})\n'
            return
        time.sleep(_FOLLOW_POLL_SECONDS)
        waited += _FOLLOW_POLL_SECONDS
    with open(path, 'r', encoding='utf-8', errors='replace') as f:
        if lines_from_end is not None:
            for line in f.readlines()[-lines_from_end:]:
                yield line
            if not follow:
                return
        while True:
            line = f.readline()
            if line:
                yield line
                continue
            if not follow:
                return
            job = job_lib.get_job(job_id)
            if job['status'].is_terminal():
                # Drain anything written between checks.
                rest = f.read()
                if rest:
                    yield rest
                yield (f'(job {job_id} finished: {job["status"].value})\n')
                return
            time.sleep(_FOLLOW_POLL_SECONDS)
