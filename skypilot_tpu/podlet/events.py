"""Daemon events: interval-gated periodic work on the head host.

Parity: sky/skylet/events.py — JobSchedulerEvent + AutostopEvent; the
managed-jobs and serve update events are registered by the respective
controller planes when they run on a controller VM.
"""
import time

from skypilot_tpu import logsys
from skypilot_tpu.podlet import autostop_lib, job_lib

logger = logsys.init_logger(__name__)


class PodletEvent:
    """Base: run() no more often than every `interval_seconds`."""
    interval_seconds = 20

    def __init__(self):
        self._last = 0.0

    def maybe_run(self) -> None:
        now = time.time()
        if now - self._last >= self.interval_seconds:
            self._last = now
            try:
                self.run()
            except Exception as e:  # pylint: disable=broad-except
                logger.error('%s failed: %s', type(self).__name__, e)

    def run(self) -> None:
        raise NotImplementedError


class JobSchedulerEvent(PodletEvent):
    """Pops the next pending job when a slot is free.

    TPU slices run one job at a time (the job owns the chips); chip-less
    controller VMs run up to ``_CONTROLLER_PARALLELISM`` jobs concurrently —
    each managed job / serve service is one long-lived podlet job.
    """
    interval_seconds = 2

    _CONTROLLER_PARALLELISM = 16

    def __init__(self):
        super().__init__()
        self._max_parallel = None

    def _resolve_max_parallel(self) -> int:
        if self._max_parallel is None:
            try:
                from skypilot_tpu.podlet import driver as driver_lib
                info = driver_lib.load_cluster_info()
                chips = info.chips_per_host or 0
            except Exception:  # pylint: disable=broad-except
                # cluster_info.json missing/corrupt (e.g. mid-rewrite):
                # fall back to the safe serial default WITHOUT caching, and
                # retry resolution next tick.
                return 1
            self._max_parallel = (1 if chips > 0 else
                                  self._CONTROLLER_PARALLELISM)
        return self._max_parallel

    def run(self) -> None:
        job_lib.schedule_step(self._resolve_max_parallel())


class AutostopEvent(PodletEvent):
    """Idle-timeout self-teardown.

    The head host tears down its own cluster using the provider metadata the
    provisioner embedded in cluster_info.json (parity:
    sky/skylet/events.py:90 AutostopEvent, which reaches the cloud API from
    the head node with mounted credentials).
    """
    interval_seconds = 20

    def __init__(self):
        super().__init__()
        # A resumed cluster still carries the PREVIOUS life's
        # autostop.json (idle, set_at long past): without counting this
        # daemon's own boot as activity, the first tick would re-stop
        # the cluster while the resuming launch is still in SETUP.
        self._boot = time.time()

    def run(self) -> None:
        if time.time() - self._boot < self.interval_seconds:
            return   # startup grace: never fire on the boot tick
        config = autostop_lib.get_autostop_config()
        if config is None or config.idle_minutes < 0:
            return
        if not job_lib.is_idle():
            return
        idle_since = max(job_lib.last_activity_time(), config.set_at,
                        self._boot)
        idle_minutes = (time.time() - idle_since) / 60.0
        if idle_minutes < config.idle_minutes:
            return
        logger.info('Idle for %.1f min >= %s min: tearing down.',
                    idle_minutes, config.idle_minutes)
        self._teardown(down=config.down)

    def _teardown(self, down: bool) -> None:
        import os

        from skypilot_tpu.podlet import driver as driver_lib
        info = driver_lib.load_cluster_info()
        # The local provider needs the client's state root, passed through
        # the daemon environment at start.
        if info.provider == 'local':
            client_home = info.custom.get('skytpu_home')
            if client_home:
                os.environ['SKYTPU_HOME'] = client_home
        from skypilot_tpu import provision
        if down or info.accelerator is not None:
            provision.terminate_instances(info.provider, info.cluster_name)
        else:
            provision.stop_instances(info.provider, info.cluster_name)
        # The cluster (including this daemon's host) is gone/stopping; exit
        # cleanly.  SystemExit passes through maybe_run's exception guard.
        # Drop the pid file first so a stop->resume's liveness probe
        # cannot race our (possibly zombie-lingering) exit.
        from skypilot_tpu.podlet import daemon as daemon_lib
        try:
            os.remove(os.path.expanduser(daemon_lib.PID_FILE))
        except OSError:
            pass
        logger.info('Autostop teardown complete; podlet exiting.')
        raise SystemExit(0)
