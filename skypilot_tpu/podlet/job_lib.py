"""Per-slice job table + FIFO scheduler (runs on the head host).

Parity: sky/skylet/job_lib.py — SQLite job table, JobStatus state machine,
FIFO scheduling, idleness for autostop, and the client→head codegen twin
(see podlet/codegen.py).  One job runs at a time: a job owns all the
slice's chips (TPU chips are not shareable the way GPUs are).

All paths are under '~' so the same code serves real head hosts (HOME=VM
home) and local simulated hosts (HOME=host dir).
"""
import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

_DB_PATH = '~/.skytpu/podlet/jobs.db'


class JobStatus(enum.Enum):
    """Parity: sky/skylet/job_lib.py:101."""
    INIT = 'INIT'
    PENDING = 'PENDING'
    SETTING_UP = 'SETTING_UP'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    FAILED_SETUP = 'FAILED_SETUP'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    @classmethod
    def from_str(cls, s: str) -> 'JobStatus':
        return cls(s)


_TERMINAL = {
    JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.FAILED_SETUP,
    JobStatus.CANCELLED
}


def _db() -> sqlite3.Connection:
    path = os.path.expanduser(_DB_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite3.connect(path, timeout=10.0)
    conn.execute('PRAGMA journal_mode=WAL')
    conn.execute("""CREATE TABLE IF NOT EXISTS jobs (
        job_id INTEGER PRIMARY KEY AUTOINCREMENT,
        job_name TEXT,
        username TEXT,
        submitted_at REAL,
        status TEXT,
        run_timestamp TEXT,
        start_at REAL,
        end_at REAL,
        pid INTEGER DEFAULT -1,
        spec TEXT DEFAULT '{}')""")
    conn.commit()
    return conn


def jobs_dir(job_id: int) -> str:
    return os.path.expanduser(f'~/.skytpu/jobs/{job_id}')


def log_dir(run_timestamp: str) -> str:
    return os.path.expanduser(f'~/sky_logs/{run_timestamp}')


# ------------------------------------------------------------- job lifecycle


def add_job(job_name: str, username: str, run_timestamp: str,
            spec: Dict[str, Any]) -> int:
    """Create an INIT job; returns job id.  Called via codegen from the
    client before the job bundle is uploaded."""
    conn = _db()
    with conn:
        cur = conn.execute(
            'INSERT INTO jobs (job_name, username, submitted_at, status,'
            ' run_timestamp, spec) VALUES (?,?,?,?,?,?)',
            (job_name, username, time.time(), JobStatus.INIT.value,
             run_timestamp, json.dumps(spec)))
        job_id = cur.lastrowid
    os.makedirs(jobs_dir(job_id), exist_ok=True)
    os.makedirs(log_dir(run_timestamp), exist_ok=True)
    return int(job_id)


def queue_job(job_id: int) -> None:
    set_status(job_id, JobStatus.PENDING)


def set_status(job_id: int, status: JobStatus) -> None:
    conn = _db()
    with conn:
        if status == JobStatus.RUNNING:
            conn.execute(
                'UPDATE jobs SET status=?, start_at=? WHERE job_id=?',
                (status.value, time.time(), job_id))
        elif status in _TERMINAL:
            conn.execute(
                'UPDATE jobs SET status=?, end_at=? WHERE job_id=?'
                ' AND end_at IS NULL',
                (status.value, time.time(), job_id))
            conn.execute('UPDATE jobs SET status=? WHERE job_id=?',
                         (status.value, job_id))
        else:
            conn.execute('UPDATE jobs SET status=? WHERE job_id=?',
                         (status.value, job_id))


def set_pid(job_id: int, pid: int) -> None:
    with _db() as conn:
        conn.execute('UPDATE jobs SET pid=? WHERE job_id=?', (pid, job_id))


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    row = _db().execute('SELECT * FROM jobs WHERE job_id=?',
                        (job_id,)).fetchone()
    return _row_to_dict(row) if row else None


def get_latest_job_id() -> Optional[int]:
    row = _db().execute(
        'SELECT job_id FROM jobs ORDER BY job_id DESC LIMIT 1').fetchone()
    return row[0] if row else None


def get_jobs(statuses: Optional[List[JobStatus]] = None
             ) -> List[Dict[str, Any]]:
    if statuses:
        qs = ','.join('?' for _ in statuses)
        rows = _db().execute(
            f'SELECT * FROM jobs WHERE status IN ({qs})'
            ' ORDER BY job_id DESC', [s.value for s in statuses]).fetchall()
    else:
        rows = _db().execute(
            'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
    return [_row_to_dict(r) for r in rows]


def _row_to_dict(row) -> Dict[str, Any]:
    (job_id, job_name, username, submitted_at, status, run_timestamp,
     start_at, end_at, pid, spec) = row
    return {
        'job_id': job_id,
        'job_name': job_name,
        'username': username,
        'submitted_at': submitted_at,
        'status': JobStatus(status),
        'run_timestamp': run_timestamp,
        'start_at': start_at,
        'end_at': end_at,
        'pid': pid,
        'spec': json.loads(spec or '{}'),
    }


def cancel_jobs(job_ids: Optional[List[int]] = None) -> List[int]:
    """Cancel specific jobs (or all non-terminal): kill the driver's
    process tree on the head host, then kill the recorded process group on
    EVERY host of the slice (the driver's ssh sessions dying does not stop
    the remote workload)."""
    from skypilot_tpu.utils import subprocess_utils
    jobs = get_jobs()
    cancelled = []
    for job in jobs:
        if job_ids is not None and job['job_id'] not in job_ids:
            continue
        if job['status'].is_terminal():
            continue
        if job['pid'] > 0:
            subprocess_utils.kill_process_tree(job['pid'])
        try:
            from skypilot_tpu.podlet import driver as driver_lib
            driver_lib.cancel_job_on_all_hosts(job['job_id'])
        except Exception:  # pylint: disable=broad-except
            pass  # cluster info may be missing (e.g. unit tests)
        set_status(job['job_id'], JobStatus.CANCELLED)
        cancelled.append(job['job_id'])
    return cancelled


def fail_all_in_progress_jobs() -> None:
    """Daemon restart hook: anything non-terminal is dead (its driver died
    with the old daemon).  Parity: job_lib reconciliation on skylet
    restart."""
    conn = _db()
    with conn:
        conn.execute(
            'UPDATE jobs SET status=?, end_at=? WHERE status NOT IN '
            f'({",".join(repr(s.value) for s in _TERMINAL)})',
            (JobStatus.FAILED.value, time.time()))


# ---------------------------------------------------------------- scheduler


def schedule_step(max_parallel: int = 1) -> Optional[int]:
    """FIFO: if a slot is free, launch the oldest PENDING job's driver.
    Returns the launched job id (or None).

    ``max_parallel`` is 1 on TPU slices (a job owns all the chips) and >1 on
    chip-less controller VMs, which run many managed-job / serve processes
    concurrently (parity: the reference's CPU/memory-based job scheduling on
    controller clusters, sky/skylet/job_lib.py:183)."""
    import subprocess
    import sys
    conn = _db()
    active = conn.execute(
        'SELECT COUNT(*) FROM jobs WHERE status IN (?,?)',
        (JobStatus.SETTING_UP.value, JobStatus.RUNNING.value)).fetchone()[0]
    if active >= max_parallel:
        return None
    row = conn.execute(
        'SELECT job_id FROM jobs WHERE status=? ORDER BY job_id LIMIT 1',
        (JobStatus.PENDING.value,)).fetchone()
    if row is None:
        return None
    job_id = int(row[0])
    set_status(job_id, JobStatus.SETTING_UP)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.podlet.driver', '--job-id',
         str(job_id)],
        stdout=open(os.path.join(jobs_dir(job_id), 'driver.log'), 'a',
                    encoding='utf-8'),
        stderr=subprocess.STDOUT,
        start_new_session=True,
        env=os.environ.copy(),
    )
    set_pid(job_id, proc.pid)
    return job_id


# ----------------------------------------------------------------- idleness


def is_idle() -> bool:
    """True if no job is queued or running (autostop input).
    Parity: is_cluster_idle (sky/skylet/job_lib.py:648)."""
    conn = _db()
    active = conn.execute(
        'SELECT COUNT(*) FROM jobs WHERE status IN (?,?,?,?)',
        (JobStatus.INIT.value, JobStatus.PENDING.value,
         JobStatus.SETTING_UP.value, JobStatus.RUNNING.value)).fetchone()[0]
    return active == 0


def last_activity_time() -> float:
    row = _db().execute(
        'SELECT MAX(COALESCE(end_at, submitted_at)) FROM jobs').fetchone()
    return row[0] or 0.0


def format_job_queue(jobs: List[Dict[str, Any]]) -> str:
    lines = [f'{"ID":<5}{"NAME":<22}{"SUBMITTED":<22}{"STATUS":<14}{"LOG"}']
    for j in jobs:
        ts = time.strftime('%Y-%m-%d %H:%M:%S',
                           time.localtime(j['submitted_at']))
        lines.append(f'{j["job_id"]:<5}{(j["job_name"] or "-")[:20]:<22}'
                     f'{ts:<22}{j["status"].value:<14}'
                     f'~/sky_logs/{j["run_timestamp"]}/')
    return '\n'.join(lines)
