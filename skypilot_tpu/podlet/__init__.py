"""podlet: the on-slice runtime (head-host daemon + job queue + gang driver).

Parity: sky/skylet/ — but with Ray removed.  A TPU pod slice is already
gang-scheduled by the hardware: one provisioning call yields M hosts wired
by ICI, so Ray's placement groups solve a problem TPUs don't have
(SURVEY.md §7).  Job execution is a direct fan-out of the run script to all
hosts with rank/coordinator env exported; XLA collectives handle the data
plane.
"""
PODLET_VERSION = 1
