"""Per-job gang driver: fans the run script out to every host of the slice.

Parity: the generated Ray driver program (RayCodeGen,
sky/backends/cloud_vm_ray_backend.py:209-688) — redesigned without Ray:

- no placement group: the slice's hosts are fixed at provision time and
  recorded in ~/.skytpu/cluster_info.json by the provisioner;
- per-host env export: SKYTPU_NODE_RANK (stable IP-sorted order),
  SKYTPU_NODE_IPS, coordinator address for jax.distributed — parity with
  the reference's rank/IP export (:494-515);
- gang failure semantics: first host to fail triggers termination of the
  job on all other hosts (parity: get_or_fail, :294-328);
- log fan-in: each host's output streams back over the runner connection
  into ~/sky_logs/<run>/tasks/host<i>.log on the head host, plus a merged
  run.log with [hostN] prefixes (solves multi-host log fan-in without a
  driver framework — SURVEY.md §7 hard part (d)).

Runs ON the head host, spawned by job_lib.schedule_step.
"""
import argparse
import concurrent.futures
import json
import os
import shlex
import sys
import threading
from typing import Dict, List, Optional

from skypilot_tpu.podlet import job_lib
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.utils import common

CLUSTER_INFO_PATH = '~/.skytpu/cluster_info.json'


def load_cluster_info() -> ClusterInfo:
    with open(os.path.expanduser(CLUSTER_INFO_PATH), 'r',
              encoding='utf-8') as f:
        return ClusterInfo.from_json(f.read())


def _make_runners(info: ClusterInfo):
    """Head-local runners to every host (including itself)."""
    if info.provider == 'local':
        from skypilot_tpu.utils.command_runner import LocalProcessRunner
        return [
            LocalProcessRunner(inst.local_dir, inst.instance_id)
            for inst in info.instances
        ]
    if info.provider == 'kubernetes':
        # The driver runs INSIDE the head pod; host 0 is plain local
        # execution.  Worker pods carry no sshd and no kubectl, so the
        # fan-out rides the podlet agent the provisioner started on
        # every worker (podlet/agent.py), over pod IPs.
        from skypilot_tpu.podlet.agent import AGENT_PORT_BASE
        from skypilot_tpu.utils.command_runner import (LocalProcessRunner,
                                                       PodAgentRunner)
        runners = [LocalProcessRunner(os.path.expanduser('~'),
                                      info.instances[0].instance_id)]
        token = info.custom.get('agent_token', '')
        base = int(info.custom.get('agent_port_base', AGENT_PORT_BASE))
        for rank, inst in enumerate(info.instances[1:], start=1):
            runners.append(
                PodAgentRunner(inst.internal_ip, base + rank, token,
                               node_id=inst.instance_id))
        return runners
    from skypilot_tpu.utils.command_runner import SSHCommandRunner
    # On the head host we reach workers over INTERNAL IPs with the key the
    # provisioner placed at ~/.ssh/skytpu-key.
    return [
        SSHCommandRunner(ip=inst.internal_ip,
                         ssh_user=info.ssh_user,
                         ssh_private_key='~/.ssh/skytpu-key')
        for inst in info.instances
    ]


def build_host_env(info: ClusterInfo, rank: int, job_id: int,
                   task_id: str, user_envs: Dict[str, str]
                   ) -> Dict[str, str]:
    """Env for host `rank` (global, slice-major order).

    Multi-slice (num_nodes > 1): every host of every slice joins ONE
    jax.distributed job — the coordinator is slice 0's first host, process
    ids are global ranks, and SKYTPU_SLICE_ID/NUM_SLICES describe the DCN
    topology (ICI within a slice, DCN between slices — megascale-style,
    parity: the reference's rank/IP export, cloud_vm_ray_backend.py:494).
    """
    ips = info.internal_ips()
    slice_id = rank // info.hosts_per_slice
    env = dict(user_envs)
    env.update({
        common.ENV_VAR_NODE_RANK: str(rank),
        common.ENV_VAR_NODE_IPS: '\n'.join(ips),
        common.ENV_VAR_NUM_NODES: str(len(ips)),
        common.ENV_VAR_NUM_CHIPS_PER_NODE: str(info.chips_per_host),
        common.ENV_VAR_TASK_ID: task_id,
        common.ENV_VAR_CLUSTER_NAME: info.cluster_name,
        common.ENV_VAR_COORDINATOR_ADDRESS:
            f'{ips[0]}:{common.JAX_COORDINATOR_PORT}',
        common.ENV_VAR_PROCESS_ID: str(rank),
        common.ENV_VAR_NUM_PROCESSES: str(len(ips)),
        common.ENV_VAR_SLICE_ID: str(slice_id),
        common.ENV_VAR_NUM_SLICES: str(info.num_slices),
        'SKYTPU_INTERNAL_JOB_ID': str(job_id),
    })
    if info.num_slices > 1:
        # Real Cloud TPU multislice: libtpu's DCN transport initializes
        # from the literal MEGASCALE_* variables when
        # jax.distributed.initialize() runs — without them, a multi-slice
        # jax job silently trains as num_slices ISOLATED jobs.  Only
        # emitted when there genuinely are >1 slices: setting them on a
        # single slice makes libtpu wait for a nonexistent peer.
        # (docs/multislice.md has the recipe.)
        env.update({
            common.ENV_VAR_MEGASCALE_COORDINATOR:
                f'{ips[0]}:{common.MEGASCALE_PORT}',
            common.ENV_VAR_MEGASCALE_NUM_SLICES: str(info.num_slices),
            common.ENV_VAR_MEGASCALE_SLICE_ID: str(slice_id),
            common.ENV_VAR_MEGASCALE_PORT: str(common.MEGASCALE_PORT),
        })
    return env


def _wrap_with_supervisor(job_id: int, rank: int, run_script_remote: str,
                          supervisor_bin: str) -> str:
    """Command that runs the job under the native supervisor when the
    host has one, else falls back to a recorded-pgid plain shell.

    The supervisor (native/src/supervisor.cc) runs the script in its own
    session, tees output to a HOST-LOCAL log (survives a dropped ssh
    connection), writes the true process-group id for gang-cancel, and
    reaps surviving grandchildren — the roles the reference delegates to
    Ray worker management + sky/skylet/subprocess_daemon.py.
    """
    job_dir = f'~/.skytpu/jobs/{job_id}'
    pgid_file = f'{job_dir}/host{rank}.pgid'
    local_log = f'{job_dir}/host{rank}.local.log'
    return (f'mkdir -p {job_dir} && '
            f'if [ -x {supervisor_bin} ]; then '
            f'exec {supervisor_bin} --log {local_log} '
            f'--pgid-file {pgid_file} -- bash {run_script_remote}; '
            f'else echo $$ > {pgid_file} && '
            f'exec bash {run_script_remote}; fi')


def _run_on_host(runner, rank: int, job_id: int, run_script_remote: str,
                 env: Dict[str, str], host_log: str,
                 merged_log_lock: threading.Lock, merged_log_path: str,
                 cancel_event: threading.Event) -> int:
    """Run the job on one host, teeing output to per-host + merged logs."""

    def _hook_factory():
        merged = open(merged_log_path, 'a', encoding='utf-8')

        def hook(line: str) -> None:
            with merged_log_lock:
                merged.write(f'[host{rank}] {line}')
                merged.flush()

        return hook

    from skypilot_tpu import native
    from skypilot_tpu.utils import subprocess_utils
    from skypilot_tpu.utils.command_runner import (LocalProcessRunner,
                                                   PodAgentRunner)
    if isinstance(runner, PodAgentRunner):
        # Worker pod: the agent execs + streams; env travels in the
        # protocol (no shell-quoting round trip).  The supervisor was
        # built during runtime sync when the image has a compiler; slim
        # images take the recorded-pgid shell fallback.
        wrapped = _wrap_with_supervisor(job_id, rank, run_script_remote,
                                        '$HOME/.skytpu/native/bin/'
                                        f'{native.SUPERVISOR_NAME}')
        env_full = {k: str(v) for k, v in env.items()}
        return runner.stream_run(wrapped, env_full, host_log,
                                 _hook_factory())
    if isinstance(runner, LocalProcessRunner):
        # Same machine: use the client-built binary by absolute path (the
        # per-host fake $HOME has no native/bin of its own).
        sup = native.supervisor_path() or '/nonexistent'
        wrapped = _wrap_with_supervisor(job_id, rank, run_script_remote, sup)
        rc, _ = subprocess_utils.run_with_log(
            ['bash', '-c', wrapped],
            host_log,
            env={**os.environ, 'HOME': runner.host_dir, **env},
            line_hook=_hook_factory(),
        )
        return rc
    # SSH runner: env is exported inline; output streams over the ssh pipe.
    # The supervisor was built on the host at provision time
    # (native.host_build_script); a compiler-less host falls back.
    wrapped = _wrap_with_supervisor(job_id, rank, run_script_remote,
                                    '$HOME/.skytpu/native/bin/'
                                    f'{native.SUPERVISOR_NAME}')
    exports = ' '.join(
        f'export {k}={shlex.quote(str(v))};' for k, v in env.items())
    rc, _ = subprocess_utils.run_with_log(
        runner._ssh_base() +  # pylint: disable=protected-access
        ['bash', '--login', '-c',
         shlex.quote(f'{exports} {wrapped}')],
        host_log,
        line_hook=_hook_factory(),
    )
    return rc


def cancel_job_on_all_hosts(job_id: int) -> None:
    """Kill the job's recorded process group on every host of the slice.
    Called by job_lib.cancel_jobs (parity: the reference's force-cancel of
    all gang members + subprocess_daemon grandchild reaping)."""
    info = load_cluster_info()
    runners = _make_runners(info)
    for rank, runner in enumerate(runners):
        _cancel_on_host(runner, rank, job_id)


def _cancel_on_host(runner, rank: int, job_id: int) -> None:
    pgid_file = f'~/.skytpu/jobs/{job_id}/host{rank}.pgid'
    cmd = (f'if [ -f {pgid_file} ]; then '
           f'kill -TERM -$(cat {pgid_file}) 2>/dev/null || true; fi')
    try:
        runner.run(cmd)
    except Exception:  # pylint: disable=broad-except
        pass


def run_job(job_id: int) -> int:
    job = job_lib.get_job(job_id)
    assert job is not None, f'job {job_id} missing'
    spec = job['spec']
    info = load_cluster_info()
    runners = _make_runners(info)
    run_timestamp = job['run_timestamp']
    task_id = spec.get('task_id') or common.make_task_id(
        job['job_name'], job_id)
    user_envs = spec.get('envs', {})

    tasks_log_dir = os.path.join(job_lib.log_dir(run_timestamp), 'tasks')
    os.makedirs(tasks_log_dir, exist_ok=True)
    merged_log = os.path.join(job_lib.log_dir(run_timestamp), 'run.log')

    # Distribute the run script to every worker host (head already has it).
    run_script_local = os.path.join(job_lib.jobs_dir(job_id), 'run.sh')
    run_script_remote = f'~/.skytpu/jobs/{job_id}/run.sh'
    for runner in runners[1:]:
        runner.rsync(run_script_local, run_script_remote, up=True)

    job_lib.set_status(job_id, job_lib.JobStatus.RUNNING)
    cancel_event = threading.Event()
    merged_lock = threading.Lock()
    returncodes: List[Optional[int]] = [None] * len(runners)

    def _worker(i: int) -> int:
        env = build_host_env(info, i, job_id, task_id, user_envs)
        host_log = os.path.join(tasks_log_dir, f'host{i}.log')
        rc = _run_on_host(runners[i], i, job_id, run_script_remote, env,
                          host_log, merged_lock, merged_log, cancel_event)
        returncodes[i] = rc
        if rc != 0 and not cancel_event.is_set():
            # Gang semantics: first failure cancels every other host.
            cancel_event.set()
            for j, other in enumerate(runners):
                if j != i:
                    _cancel_on_host(other, j, job_id)
        return rc

    with concurrent.futures.ThreadPoolExecutor(
            max_workers=len(runners)) as pool:
        futures = [pool.submit(_worker, i) for i in range(len(runners))]
        for f in futures:
            f.result()

    if cancel_event.is_set():
        job_lib.set_status(job_id, job_lib.JobStatus.FAILED)
        bad = [i for i, rc in enumerate(returncodes) if rc not in (0, None)]
        with open(merged_log, 'a', encoding='utf-8') as f:
            f.write(f'[driver] job failed on host(s) {bad}; '
                    f'returncodes={returncodes}\n')
        return 1
    job_lib.set_status(job_id, job_lib.JobStatus.SUCCEEDED)
    return 0


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    args = parser.parse_args()
    try:
        rc = run_job(args.job_id)
    except Exception as e:  # pylint: disable=broad-except
        job_lib.set_status(args.job_id, job_lib.JobStatus.FAILED)
        print(f'[driver] exception: {e}', file=sys.stderr)
        raise
    sys.exit(rc)


if __name__ == '__main__':
    main()
