"""Client→head-host RPC by generated Python snippets.

Parity: JobLibCodeGen (sky/skylet/job_lib.py:810) — the client has no
daemon connection to the cluster; it executes short python programs on the
head host over the command runner, with results returned on stdout between
sentinel markers.
"""
import json
import shlex
from typing import Any, Dict, List, Optional

RESULT_BEGIN = '<<<SKYTPU_RESULT>>>'
RESULT_END = '<<<END_SKYTPU_RESULT>>>'

_RUNTIME_PYTHONPATH = '~/.skytpu_runtime'

_PRELUDE = """\
import json, os, sys, time
sys.path.insert(0, os.path.expanduser('{pythonpath}'))
{imports}
def _emit(obj):
    print({begin!r}); print(json.dumps(obj)); print({end!r})
"""


def wrap_python(body: str, imports: str) -> str:
    """Build a `python3 -c` shell command that runs ``body`` on a host with
    the framework runtime on its path, emitting results between sentinel
    markers (shared by the podlet, jobs, and serve codegen twins)."""
    prelude = _PRELUDE.format(pythonpath=_RUNTIME_PYTHONPATH,
                              imports=imports,
                              begin=RESULT_BEGIN, end=RESULT_END)
    return f'python3 -u -c {shlex.quote(prelude + body)}'


def _wrap(body: str) -> str:
    return wrap_python(
        body, 'from skypilot_tpu.podlet import job_lib, log_lib, '
        'autostop_lib')


def parse_result(stdout: str) -> Any:
    begin = stdout.rfind(RESULT_BEGIN)
    end = stdout.rfind(RESULT_END)
    if begin == -1 or end == -1 or end < begin:
        raise ValueError(f'No codegen result markers in output: '
                         f'{stdout[-1000:]!r}')
    payload = stdout[begin + len(RESULT_BEGIN):end].strip()
    return json.loads(payload)


class JobCodeGen:
    """Builders returning shell commands to run on the head host."""

    @staticmethod
    def add_job(job_name: str, username: str, run_timestamp: str,
                spec: Dict[str, Any]) -> str:
        body = (f'job_id = job_lib.add_job({job_name!r}, {username!r}, '
                f'{run_timestamp!r}, json.loads({json.dumps(spec)!r}))\n'
                f'_emit({{"job_id": job_id}})\n')
        return _wrap(body)

    @staticmethod
    def queue_job(job_id: int) -> str:
        body = (f'job_lib.queue_job({job_id})\n'
                f'_emit({{"ok": True}})\n')
        return _wrap(body)

    @staticmethod
    def get_job_queue(all_jobs: bool = True) -> str:
        body = (
            'jobs = job_lib.get_jobs()\n'
            'out = [dict(j, status=j["status"].value) for j in jobs]\n'
            '_emit(out)\n')
        return _wrap(body)

    @staticmethod
    def get_job_status(job_id: Optional[int] = None) -> str:
        body = (
            f'jid = {job_id!r}\n'
            'jid = jid if jid is not None else job_lib.get_latest_job_id()\n'
            'job = job_lib.get_job(jid) if jid is not None else None\n'
            '_emit({"job_id": jid, '
            '"status": job["status"].value if job else None})\n')
        return _wrap(body)

    @staticmethod
    def cancel_jobs(job_ids: Optional[List[int]] = None) -> str:
        body = (f'cancelled = job_lib.cancel_jobs({job_ids!r})\n'
                f'_emit({{"cancelled": cancelled}})\n')
        return _wrap(body)

    @staticmethod
    def tail_logs(job_id: Optional[int] = None, follow: bool = True,
                  lines_from_end: Optional[int] = None) -> str:
        # Streams raw log lines (no result markers: output IS the payload).
        body = (
            f'for line in log_lib.tail_logs({job_id!r}, follow={follow!r}, '
            f'lines_from_end={lines_from_end!r}):\n'
            f'    sys.stdout.write(line); sys.stdout.flush()\n')
        return _wrap(body)

    @staticmethod
    def set_autostop(idle_minutes: int, down: bool) -> str:
        body = (f'autostop_lib.set_autostop({idle_minutes}, {down})\n'
                f'_emit({{"ok": True}})\n')
        return _wrap(body)

    @staticmethod
    def is_idle() -> str:
        body = '_emit({"idle": job_lib.is_idle()})\n'
        return _wrap(body)
