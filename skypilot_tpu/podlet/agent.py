"""Worker-pod exec agent: the gang driver's transport on Kubernetes.

TPU-VM hosts run sshd, so the head-host driver fans jobs out over SSH
(podlet/driver.py).  Kubernetes pods carry neither sshd nor kubectl, so
multi-host podslices need their own intra-cluster transport (the
reference reaches pods from the *client* via the kubernetes API,
sky/provision/kubernetes/instance.py:921 — but the gang driver runs ON
the head pod, inside the cluster).  This agent is that transport: a
small JSON-lines-over-TCP server the provisioner starts on every worker
pod, listening on the pod network (headless-service DNS / pod IP).

Protocol (one JSON object per line, newline-terminated):
  -> {"token": t, "op": "ping"}
  <- {"ok": true}
  -> {"token": t, "op": "put", "path": p, "data": b64, "mode": 0o644}
  <- {"ok": true}
  -> {"token": t, "op": "run", "cmd": c, "env": {...}}
  <- {"line": "..."} ... streamed as the command prints ...
  <- {"rc": 0}

Auth: a per-cluster random token the provisioner writes to
~/.skytpu/agent_token on every pod before the agent starts (the pod
network is cluster-internal, but a flat network is no reason to run an
unauthenticated exec service).  One request per connection.
"""
import argparse
import base64
import json
import os
import socketserver
import subprocess
import sys

TOKEN_PATH = '~/.skytpu/agent_token'
# Worker rank i listens on AGENT_PORT_BASE + i: per-rank ports keep the
# scheme collision-free even when several pods share one IP (the
# hermetic test seam runs every "pod" on localhost).  8490+ avoids the
# jax coordinator (8476) and MEGASCALE (8477) ports.
AGENT_PORT_BASE = 8490


def _load_token() -> str:
    with open(os.path.expanduser(TOKEN_PATH), 'r', encoding='utf-8') as f:
        return f.read().strip()


class _Handler(socketserver.StreamRequestHandler):

    def _send(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + '\n').encode())
        self.wfile.flush()

    def handle(self) -> None:
        try:
            line = self.rfile.readline(10 * 1024 * 1024)
            req = json.loads(line)
        except (ValueError, OSError):
            return
        # Token is re-read per request: a client that regenerates the
        # cluster token (state wipe, second client machine) rewrites
        # ~/.skytpu/agent_token and must NOT be locked out by a value
        # the agent cached at startup.
        try:
            expected = _load_token()
        except OSError:
            expected = None
        if expected is None or req.get('token') != expected:
            self._send({'error': 'bad token'})
            return
        op = req.get('op')
        try:
            if op == 'ping':
                self._send({'ok': True})
            elif op == 'put':
                path = os.path.expanduser(req['path'])
                os.makedirs(os.path.dirname(path) or '/', exist_ok=True)
                with open(path, 'wb') as f:
                    f.write(base64.b64decode(req['data']))
                os.chmod(path, int(req.get('mode', 0o644)))
                self._send({'ok': True})
            elif op == 'run':
                env = dict(os.environ)
                env.update({str(k): str(v)
                            for k, v in (req.get('env') or {}).items()})
                # start_new_session: the job must lead its own process
                # group — the recorded-pgid cancel fallback (`kill -TERM
                # -$(cat pgid_file)`) is a no-op on a non-leader, which
                # would leave cancelled gang jobs burning the podslice.
                proc = subprocess.Popen(
                    ['sh', '-c', req['cmd']], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, errors='replace', start_new_session=True)
                assert proc.stdout is not None
                for out_line in proc.stdout:
                    self._send({'line': out_line.rstrip('\n')})
                self._send({'rc': proc.wait()})
            else:
                self._send({'error': f'unknown op {op!r}'})
        except Exception as e:  # pylint: disable=broad-except
            try:
                self._send({'error': str(e), 'rc': 113})
            except OSError:
                pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--port', type=int, required=True)
    parser.add_argument('--host', default='0.0.0.0')
    args = parser.parse_args()
    server = _Server((args.host, args.port), _Handler)
    _load_token()                   # fail fast if the token is missing
    print(f'[agent] listening on {args.host}:{args.port}', flush=True)
    server.serve_forever()


if __name__ == '__main__':
    sys.exit(main())
