"""The podlet daemon: head-host event loop.

Parity: sky/skylet/skylet.py:17-33 — a 2-second loop over registered
events.  Started by the provisioner via nohup; restarted (with version
check) on reprovision (parity: sky/skylet/attempt_skylet.py).
"""
import os
import time

from skypilot_tpu import logsys
from skypilot_tpu.podlet import PODLET_VERSION, events, job_lib

logger = logsys.init_logger(__name__)

_LOOP_SECONDS = 2
VERSION_FILE = '~/.skytpu/podlet/version'
PID_FILE = '~/.skytpu/podlet/pid'


def write_version() -> None:
    path = os.path.expanduser(VERSION_FILE)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        f.write(str(PODLET_VERSION))


def main() -> None:
    write_version()
    pid_path = os.path.expanduser(PID_FILE)
    with open(pid_path, 'w', encoding='utf-8') as f:
        f.write(str(os.getpid()))
    # Jobs that were mid-flight when the previous daemon died are dead.
    job_lib.fail_all_in_progress_jobs()
    evts = [events.JobSchedulerEvent(), events.AutostopEvent()]
    logger.info('podlet v%s started (pid %d).', PODLET_VERSION, os.getpid())
    while True:
        for e in evts:
            e.maybe_run()
        time.sleep(_LOOP_SECONDS)


if __name__ == '__main__':
    main()
