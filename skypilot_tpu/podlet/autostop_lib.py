"""Autostop configuration on the head host.

Parity: sky/skylet/autostop_lib.py:28-78 — a small config file consulted by
the daemon's AutostopEvent; set via codegen from the client at PRE_EXEC.
For TPU slices autostop always means auto-DOWN (slices cannot stop).
"""
import dataclasses
import json
import os
import time
from typing import Optional

_CONFIG_PATH = '~/.skytpu/podlet/autostop.json'


@dataclasses.dataclass
class AutostopConfig:
    idle_minutes: int            # <0 disables autostop
    down: bool                   # terminate (True) vs stop (False)
    set_at: float


def set_autostop(idle_minutes: int, down: bool) -> None:
    path = os.path.expanduser(_CONFIG_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(
            {
                'idle_minutes': idle_minutes,
                'down': down,
                'set_at': time.time()
            }, f)


def get_autostop_config() -> Optional[AutostopConfig]:
    try:
        with open(os.path.expanduser(_CONFIG_PATH), 'r',
                  encoding='utf-8') as f:
            d = json.load(f)
        return AutostopConfig(**d)
    except (FileNotFoundError, json.JSONDecodeError, TypeError):
        return None
