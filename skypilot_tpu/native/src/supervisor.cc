// skytpu-supervisor: native per-host job supervisor.
//
// Runs a command in its OWN SESSION (setsid), tees its merged
// stdout/stderr to a host-local log file AND to our stdout (so the ssh
// channel still streams lines back to the head host), records the
// process-group id for gang-cancel, forwards SIGTERM/SIGINT to the whole
// group, and reaps surviving grandchildren when the job ends.
//
// Role parity (reference, rebuilt native instead of Python):
//   - sky/skylet/log_lib.py:131 run_with_log  (tee loop -> C++ read/write)
//   - sky/skylet/subprocess_daemon.py         (process-tree reaping)
//   - Ray worker process management           (the reference delegates
//     job process supervision to Ray; this framework owns it)
//
// Usage:
//   skytpu-supervisor --log PATH --pgid-file PATH [--grace-ms N]
//                     -- CMD [ARGS...]
// Exit code: the child's exit code, or 128+signal if it died by signal.

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

volatile sig_atomic_t g_signal = 0;

void handle_signal(int sig) { g_signal = sig; }

void die(const char* msg) {
  perror(msg);
  exit(127);
}

// Write all of buf, retrying on short writes/EINTR. Returns false on error.
bool write_all(int fd, const char* buf, size_t n) {
  while (n > 0) {
    ssize_t w = write(fd, buf, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  const char* log_path = nullptr;
  const char* pgid_path = nullptr;
  long grace_ms = 2000;
  int cmd_start = -1;
  for (int i = 1; i < argc; i++) {
    if (!strcmp(argv[i], "--log") && i + 1 < argc) {
      log_path = argv[++i];
    } else if (!strcmp(argv[i], "--pgid-file") && i + 1 < argc) {
      pgid_path = argv[++i];
    } else if (!strcmp(argv[i], "--grace-ms") && i + 1 < argc) {
      grace_ms = atol(argv[++i]);
    } else if (!strcmp(argv[i], "--")) {
      cmd_start = i + 1;
      break;
    } else {
      fprintf(stderr, "skytpu-supervisor: unknown arg %s\n", argv[i]);
      return 127;
    }
  }
  if (cmd_start < 0 || cmd_start >= argc) {
    fprintf(stderr,
            "usage: skytpu-supervisor --log PATH --pgid-file PATH "
            "[--grace-ms N] -- CMD [ARGS...]\n");
    return 127;
  }

  int log_fd = -1;
  if (log_path) {
    log_fd = open(log_path, O_WRONLY | O_CREAT | O_APPEND, 0644);
    if (log_fd < 0) die("skytpu-supervisor: open log");
  }

  int pipefd[2];
  if (pipe(pipefd) < 0) die("pipe");

  pid_t child = fork();
  if (child < 0) die("fork");
  if (child == 0) {
    // Child: new session => new process group; pgid == pid. Every
    // descendant the job spawns stays in this group unless it setsids
    // itself.
    setsid();
    dup2(pipefd[1], STDOUT_FILENO);
    dup2(pipefd[1], STDERR_FILENO);
    close(pipefd[0]);
    close(pipefd[1]);
    if (log_fd >= 0) close(log_fd);
    execvp(argv[cmd_start], &argv[cmd_start]);
    perror("skytpu-supervisor: execvp");
    _exit(127);
  }
  close(pipefd[1]);

  if (pgid_path) {
    FILE* f = fopen(pgid_path, "w");
    if (f) {
      fprintf(f, "%d\n", static_cast<int>(child));
      fclose(f);
    }
  }

  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = handle_signal;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);
  signal(SIGPIPE, SIG_IGN);  // head-side ssh teardown must not kill us

  bool child_exited = false;
  int child_status = 0;
  bool signaled_group = false;
  bool eof = false;
  long long drain_deadline_ms = -1;
  std::vector<char> buf(1 << 16);

  auto now_ms = []() -> long long {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<long long>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  };

  while (true) {
    if (g_signal) {
      g_signal = 0;
      if (!signaled_group) {
        // Cancel: forward to the whole group (grandchildren included).
        kill(-child, SIGTERM);
        signaled_group = true;
      } else {
        kill(-child, SIGKILL);  // second signal: escalate
      }
    }
    if (!child_exited) {
      pid_t r = waitpid(child, &child_status, WNOHANG);
      if (r == child) {
        child_exited = true;
        drain_deadline_ms = now_ms() + grace_ms;
      }
    }
    // Enforce the drain window unconditionally: a chatty surviving
    // grandchild that keeps the pipe saturated must not pin the
    // supervisor (and the gang driver waiting on it) forever.
    if (child_exited && drain_deadline_ms >= 0 &&
        now_ms() >= drain_deadline_ms) {
      break;
    }
    if (eof) {
      // Every writer closed the pipe; only the child's exit remains.
      if (child_exited) break;
      usleep(100 * 1000);
      continue;
    }
    struct pollfd pfd = {pipefd[0], POLLIN, 0};
    int timeout = child_exited ? 100 : 200;
    int pr = poll(&pfd, 1, timeout);
    if (pr > 0 && (pfd.revents & (POLLIN | POLLHUP))) {
      ssize_t n = read(pipefd[0], buf.data(), buf.size());
      if (n > 0) {
        write_all(STDOUT_FILENO, buf.data(), static_cast<size_t>(n));
        if (log_fd >= 0) write_all(log_fd, buf.data(),
                                   static_cast<size_t>(n));
        continue;
      }
      if (n == 0) {
        if (child_exited) break;  // done draining
        eof = true;  // child closed stdout but still runs
      }
    }
  }

  if (!child_exited) {
    waitpid(child, &child_status, 0);
  }
  // Reap stragglers: once the job's main process is gone, surviving
  // group members are orphans of THIS job (parity: subprocess_daemon).
  kill(-child, SIGTERM);
  usleep(50 * 1000);
  kill(-child, SIGKILL);

  if (log_fd >= 0) close(log_fd);
  if (WIFSIGNALED(child_status)) return 128 + WTERMSIG(child_status);
  return WEXITSTATUS(child_status);
}
