"""Native (C++) runtime components, built on demand on each host.

The reference delegates job-process supervision to Ray's C++ worker
management plus Python helpers (sky/skylet/log_lib.py:131 run_with_log,
sky/skylet/subprocess_daemon.py).  This framework owns that path natively:
`src/supervisor.cc` runs the job in its own session, tees output to a
host-local log (so logs survive a dropped ssh connection), records the
process-group id for gang-cancel, and reaps surviving grandchildren.

Build model: the C++ source travels with the package (the provisioner
rsyncs the whole package tree to every host), and each host compiles it
once per source hash into $SKYTPU_HOME/native/bin/ via the single build
recipe in build_host.py (stdlib-only so job hosts can run it bare).
Every consumer must tolerate a missing binary (no compiler on the host) —
the shell fallback in podlet/driver.py keeps the system working, just
without host-local log durability and true session isolation.
"""
import os
import threading
from typing import Optional

from skypilot_tpu import logsys
from skypilot_tpu.native import build_host
from skypilot_tpu.utils import locks as locks_lib

logger = logsys.init_logger(__name__)

SUPERVISOR_NAME = build_host.SUPERVISOR_NAME

_build_lock = threading.Lock()
_build_cache: dict = {}


def source_path() -> str:
    return build_host.default_source()


def source_hash() -> str:
    return build_host.source_hash(source_path())


def _bin_dir() -> str:
    from skypilot_tpu.utils import common
    return os.path.join(common.home_dir(), 'native', 'bin')


def installed_bin_path() -> str:
    """Where job hosts look for the binary ($HOME-relative, stable name)."""
    return os.path.join(_bin_dir(), SUPERVISOR_NAME)


def supervisor_path(build: bool = True) -> Optional[str]:
    """Absolute path of a supervisor binary matching the current source,
    building it if necessary.  Returns None when it cannot be produced
    (no g++ on this machine, or compilation failed) — callers must fall
    back to the pure-Python / plain-shell path.
    """
    src_hash = source_hash()
    cached = _build_cache.get(src_hash)
    if cached is not None:
        return cached or None  # '' caches a failed build
    versioned = os.path.join(_bin_dir(), f'{SUPERVISOR_NAME}-{src_hash}')
    if os.path.exists(versioned):
        _build_cache[src_hash] = versioned
        return versioned
    if not build:
        return None
    with _build_lock, locks_lib.named_lock('native-build'):
        path = build_host.build(source_path(), _bin_dir())
        if path is None:
            logger.warning('Native supervisor unavailable (no compiler or '
                           'build failed); using shell fallback.')
        _build_cache[src_hash] = path or ''
        return path


def host_build_script() -> str:
    """Shell one-liner that builds + installs the supervisor ON a job host
    by running the SAME recipe (build_host.py) with the host's python3.

    Run once per runtime sync (post_provision_runtime_setup); idempotent via
    the source-hash-named binary.  Never fails the setup: a host without a
    compiler simply runs jobs through the shell fallback.
    """
    script = ('$HOME/.skytpu_runtime/skypilot_tpu/native/build_host.py')
    return (f'if [ -f {script} ] && command -v python3 >/dev/null; then '
            f'python3 {script} || true; fi; true')
