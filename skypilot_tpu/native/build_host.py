"""Build + install the native supervisor on this machine.

The ONE implementation of the build recipe, used by (a) the client via
`skypilot_tpu.native.supervisor_path()` and (b) job hosts, where the
provisioner runs this file with the host's `python3` right after rsyncing
the runtime tree (see native.host_build_script()).  Stdlib-only on purpose:
job hosts may not have the framework's Python dependencies installed when
this runs.

Install layout: `<bindir>/skytpu-supervisor-<hash12>` (content-addressed,
idempotent) plus a stable `<bindir>/skytpu-supervisor` symlink that job
commands reference without knowing the hash.
"""
import argparse
import hashlib
import os
import shutil
import subprocess
import sys
from typing import Optional

SUPERVISOR_NAME = 'skytpu-supervisor'
CXX_FLAGS = ['-O2', '-std=c++17']


def default_source() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), 'src',
                        'supervisor.cc')


def source_hash(src: str) -> str:
    with open(src, 'rb') as f:
        return hashlib.sha256(f.read()).hexdigest()[:12]


def build(src: str, bindir: str) -> Optional[str]:
    """Compile src into bindir (idempotent); returns the versioned binary
    path, or None when no compiler is available or compilation fails."""
    compiler = shutil.which('g++') or shutil.which('c++')
    if compiler is None:
        return None
    versioned = os.path.join(bindir, f'{SUPERVISOR_NAME}-{source_hash(src)}')
    if not os.path.exists(versioned):
        os.makedirs(bindir, exist_ok=True)
        tmp = f'{versioned}.tmp.{os.getpid()}'
        proc = subprocess.run([compiler, *CXX_FLAGS, '-o', tmp, src],
                              capture_output=True, text=True, check=False)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr[:2000])
            return None
        os.replace(tmp, versioned)  # atomic: concurrent builders both win
    stable = os.path.join(bindir, SUPERVISOR_NAME)
    tmp_link = f'{stable}.tmp.{os.getpid()}'
    try:
        os.symlink(versioned, tmp_link)
        os.replace(tmp_link, stable)
    except OSError:
        pass
    return versioned


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument('--src', default=default_source())
    parser.add_argument(
        '--bindir',
        default=os.path.expanduser(
            os.path.join(os.environ.get('SKYTPU_HOME', '~/.skytpu'),
                         'native', 'bin')))
    args = parser.parse_args()
    if not os.path.exists(args.src):
        return 0  # source-less host: nothing to do, not an error
    path = build(args.src, args.bindir)
    if path is None:
        sys.stderr.write('skytpu: native supervisor unavailable '
                         '(no compiler or build failed); jobs will use the '
                         'shell fallback.\n')
        return 0  # never fail host setup over the optional native path
    print(path)
    return 0


if __name__ == '__main__':
    sys.exit(main())
