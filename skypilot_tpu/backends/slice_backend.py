"""SliceBackend: the production backend for TPU pod slices (and the local
simulated slices / controller VMs).

Parity: CloudVmRayBackend (sky/backends/cloud_vm_ray_backend.py:2539) +
RetryingVmProvisioner (:1134) + the failover error handlers (:707-1133) —
re-designed without Ray: job submission goes through podlet codegen, gang
execution through the podlet driver, and failover walks the optimizer's
ranked zone-granular candidates (stockout being the dominant TPU failure).
"""
import getpass
import os
import textwrap
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions, logsys, provision, state
from skypilot_tpu.backends.backend import Backend, ResourceHandle
from skypilot_tpu.podlet import codegen as podlet_codegen
from skypilot_tpu.provision import provisioner
from skypilot_tpu.provision.common import ClusterInfo
from skypilot_tpu.resources import Resources
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.utils import (command_runner, common, locks, ssh_config,
                                subprocess_utils, timeline, ux)

logger = logsys.init_logger(__name__)

_WORKDIR_REMOTE = '~/sky_workdir'
_PROVISION_RETRY_GAP_SECONDS = 30


class SliceResourceHandle(ResourceHandle):
    """Pickled per-cluster record.
    Parity: CloudVmRayResourceHandle (cloud_vm_ray_backend.py:2077)."""

    _VERSION = 1

    def __init__(self, cluster_name: str, launched_resources: Resources,
                 launched_nodes: int = 1):
        self._version = self._VERSION
        self.cluster_name = cluster_name
        self.launched_resources = launched_resources
        self.launched_nodes = launched_nodes  # slices (DCN gang width)
        self.stable_internal_external_ips: Optional[List] = None
        self.cached_cluster_info: Optional[Dict[str, Any]] = None
        self.run_timestamp: Optional[str] = None

    @property
    def provider(self) -> str:
        return self.launched_resources.cloud or 'gcp'

    @property
    def num_hosts(self) -> int:
        """Hosts per slice (parity: num_ips_per_node,
        cloud_vm_ray_backend.py:2469)."""
        return self.launched_resources.num_hosts

    def cluster_info(self, refresh: bool = False) -> ClusterInfo:
        if self.cached_cluster_info is None or refresh:
            info = provision.get_cluster_info(self.provider, None, None,
                                              self.cluster_name)
            self.cached_cluster_info = info.to_json()
            self.stable_internal_external_ips = list(
                zip(info.internal_ips(), info.external_ips()))
            state.update_cluster_handle(self.cluster_name, self)
        return ClusterInfo.from_json(self.cached_cluster_info)

    def get_command_runners(
            self, refresh: bool = False
    ) -> List[command_runner.CommandRunner]:
        info = self.cluster_info(refresh=refresh)
        return provision.get_command_runners(self.provider, info)

    def head_runner(self) -> command_runner.CommandRunner:
        return self.get_command_runners()[0]

    def __repr__(self):
        return (f'<SliceResourceHandle {self.cluster_name}: '
                f'{self.launched_resources.pretty()}>')


def _log_dir_for(cluster_name: str) -> str:
    d = os.path.join(common.logs_dir(), cluster_name)
    os.makedirs(d, exist_ok=True)
    return d


class RetryingProvisioner:
    """Walks optimizer-ranked candidates, consuming a blocklist.

    Parity: RetryingVmProvisioner.provision_with_retries
    (cloud_vm_ray_backend.py:1934) + FailoverCloudErrorHandlerV2 (:914):
    - stockout     -> block this zone for this accelerator
    - quota        -> block the whole region
    - non-retryable-> abort failover entirely
    """

    def __init__(self, cluster_name: str, log_path: str):
        self.cluster_name = cluster_name
        self.log_path = log_path
        self.blocked: List[Resources] = []
        self.failover_history: List[Exception] = []

    def _update_blocklist(self, resources: Resources,
                          error: Exception) -> None:
        if isinstance(error, exceptions.QuotaExceededError):
            self.blocked.append(
                Resources(cloud=resources.cloud,
                          accelerator=resources.accelerator,
                          region=resources.region,
                          use_spot=resources.use_spot))
            logger.warning('Quota exhausted: blocking region %s.',
                           resources.region)
        elif isinstance(error, exceptions.TpuStockoutError):
            self.blocked.append(
                Resources(cloud=resources.cloud,
                          accelerator=resources.accelerator,
                          region=resources.region,
                          zone=resources.zone,
                          use_spot=resources.use_spot))
            logger.warning('No capacity: blocking zone %s.', resources.zone)
        else:
            self.blocked.append(
                Resources(cloud=resources.cloud,
                          accelerator=resources.accelerator,
                          region=resources.region,
                          zone=resources.zone,
                          use_spot=resources.use_spot))

    def provision_with_retries(self, task, candidates,
                               retry_until_up: bool,
                               num_slices: Optional[int] = None):
        """Try candidates in order; returns (chosen Candidate,
        ProvisionRecord, deploy_config)."""
        num_slices = num_slices or getattr(task, 'num_nodes', 1) or 1
        from skypilot_tpu.clouds import Cloud
        while True:
            for cand in candidates:
                resources = cand.resources
                if any(
                        resources.should_be_blocked_by(b)
                        for b in self.blocked):
                    continue
                cloud = Cloud.from_name(resources.cloud)
                config = cloud.make_deploy_variables(resources,
                                                     self.cluster_name,
                                                     cand.region, cand.zone)
                # Gang width: num_nodes counts SLICES (task.py docstring);
                # each provider provisions that many slice resources and
                # reports all hosts in one ClusterInfo.
                config['num_slices'] = num_slices
                logger.info('%s Provisioning %s in %s...',
                            ux.emph('[provision]'), resources.pretty(),
                            cand.zone or cand.region)
                try:
                    record = provisioner.bulk_provision(
                        resources.cloud, cand.region, cand.zone,
                        self.cluster_name, config, self.log_path)
                    return cand, record, config
                except exceptions.ProvisionError as e:
                    self.failover_history.append(e)
                    if not e.retryable:
                        raise exceptions.ResourcesUnavailableError(
                            f'Provisioning failed with non-retryable error: '
                            f'{e}').with_failover_history(
                                self.failover_history)
                    self._update_blocklist(resources, e)
                except exceptions.ApiError as e:
                    self.failover_history.append(e)
                    self._update_blocklist(resources, e)
            if not retry_until_up:
                raise exceptions.ResourcesUnavailableError(
                    f'Failed to provision {task.name or "task"} on all '
                    f'candidate placements '
                    f'({len(self.failover_history)} attempt(s)). Errors: ' +
                    '; '.join(
                        str(e)[:200] for e in self.failover_history[-5:])
                ).with_failover_history(self.failover_history)
            logger.info(
                'Retrying provisioning in %ds (retry_until_up set)...',
                _PROVISION_RETRY_GAP_SECONDS)
            self.blocked = []  # fresh round: capacity may have appeared
            time.sleep(_PROVISION_RETRY_GAP_SECONDS)


class SliceBackend(Backend[SliceResourceHandle]):
    NAME = 'slice'

    # ------------------------------------------------------------ provision

    @timeline.event
    def provision(self, task, to_provision: Optional[Resources],
                  dryrun: bool, stream_logs: bool, cluster_name: str,
                  retry_until_up: bool = False
                  ) -> Optional[SliceResourceHandle]:
        candidates = getattr(task, 'candidates', None)
        if candidates is None:
            from skypilot_tpu import dag as dag_lib
            from skypilot_tpu import optimizer as optimizer_lib
            with dag_lib.Dag() as d:
                d.add(task)
            optimizer_lib.optimize(d, quiet=True)
            candidates = task.candidates
        if (task.num_nodes or 1) > 1:
            # Gang width (num_nodes = SLICES) is a task property the
            # per-resource feasibility check cannot see: filter clouds
            # that cannot provision multi-slice gangs HERE, before any
            # provisioning is paid for (a kubernetes podslice wait is
            # ~30 min; failing at job-run time after it is not ok).
            from skypilot_tpu.clouds import Cloud
            from skypilot_tpu.clouds.cloud import CloudCapability
            dropped = {
                c.resources.cloud for c in candidates
                if not Cloud.from_name(c.resources.cloud).supports(
                    CloudCapability.MULTI_SLICE)
            }
            candidates = [
                c for c in candidates if c.resources.cloud not in dropped
            ]
            if not candidates:
                raise exceptions.InvalidResourcesError(
                    f'num_nodes={task.num_nodes} needs a multi-slice '
                    f'capable cloud; {sorted(dropped)} cannot gang-'
                    'provision multiple slices (on kubernetes use one '
                    'slice per task, or cloud: gcp for multislice)')
        if dryrun:
            cand = candidates[0]
            logger.info('Dryrun: would provision %s in %s.',
                        cand.resources.pretty(), cand.zone or cand.region)
            return None
        log_path = os.path.join(_log_dir_for(cluster_name), 'provision.log')
        width = task.num_nodes or 1
        with locks.cluster_status_lock(cluster_name):
            existing = state.get_cluster_from_name(cluster_name)
            if existing is not None:
                # A second cloud identity must not reuse (and thereby
                # mutate) another user's cluster.
                from skypilot_tpu import backend_utils
                backend_utils.check_owner_identity(cluster_name)
                handle = existing['handle']
                launched = handle.launched_resources
                wanted_ok = any(
                    r.less_demanding_than(launched) for r in task.resources)
                if not wanted_ok:
                    raise exceptions.ResourcesMismatchError(
                        f'Cluster {cluster_name!r} exists with '
                        f'{launched.pretty()}, which does not satisfy the '
                        f'requested resources. Use a new cluster name, or '
                        f'`skytpu down {cluster_name}` first.')
                launched_width = getattr(handle, 'launched_nodes', 1) or 1
                if (task.num_nodes or 1) > launched_width:
                    raise exceptions.ResourcesMismatchError(
                        f'Cluster {cluster_name!r} has '
                        f'{handle.launched_nodes} slice(s); the task needs '
                        f'{task.num_nodes}. Use a new cluster name.')
                # Reuse keeps the cluster's existing gang width: shrinking
                # it would orphan the extra slice resources (they would
                # drop out of the provider metadata but keep billing).
                width = launched_width
                # Narrow candidates to the existing placement so a restart
                # reuses the same zone.
                candidates = [
                    c for c in candidates
                    if c.resources.zone == launched.zone
                ] or candidates
            retrier = RetryingProvisioner(cluster_name, log_path)
            cand, record, config = retrier.provision_with_retries(
                task, candidates, retry_until_up, num_slices=width)
            handle = SliceResourceHandle(cluster_name, cand.resources,
                                         launched_nodes=width)
            # Record the creating cloud identity (owner) so later
            # mutating ops can detect an account switch
            # (backend_utils.check_owner_identity).
            import json as json_lib

            from skypilot_tpu.clouds import Cloud
            identity = Cloud.from_name(
                cand.resources.cloud).get_active_user_identity()
            owner = json_lib.dumps(identity) if identity else None
            state.add_or_update_cluster(cluster_name, handle,
                                        set(task.resources), ready=False,
                                        owner=owner)
            try:
                info = provision.get_cluster_info(cand.resources.cloud,
                                                  cand.region, cand.zone,
                                                  cluster_name)
                provisioner.post_provision_runtime_setup(
                    cluster_name, info, log_path)
                if cand.resources.ports:
                    provision.open_ports(cand.resources.cloud, cluster_name,
                                         cand.resources.ports)
            except Exception:
                state.add_or_update_cluster(cluster_name, handle,
                                            set(task.resources), ready=False,
                                            is_launch=False)
                raise
            handle.cached_cluster_info = info.to_json()
            handle.stable_internal_external_ips = list(
                zip(info.internal_ips(), info.external_ips()))
            state.add_or_update_cluster(cluster_name, handle,
                                        set(task.resources), ready=True)
            # `ssh <cluster>` / `ssh <cluster>-workerN` aliases (parity:
            # SSHConfigHelper, backend_utils.py:399).
            ssh_config.add_cluster(cluster_name, info.external_ips(),
                                   info.ssh_user, info.ssh_private_key)
            logger.info('%s Cluster %r is UP (%d host(s)%s).',
                        ux.ok('[done]'), cluster_name, info.num_hosts,
                        f' across {info.num_slices} slices'
                        if info.num_slices > 1 else '')
            return handle

    # ----------------------------------------------------------- file sync

    @timeline.event
    def sync_workdir(self, handle: SliceResourceHandle, workdir: str) -> None:
        runners = handle.get_command_runners()
        src = os.path.abspath(os.path.expanduser(workdir)).rstrip('/') + '/'
        log_path = os.path.join(_log_dir_for(handle.cluster_name),
                                'sync_workdir.log')

        def _sync(runner):
            runner.rsync(src, _WORKDIR_REMOTE + '/', up=True,
                         log_path=log_path)

        logger.info('%s Syncing workdir %s -> %s on %d host(s).',
                    ux.emph('[sync]'), workdir, _WORKDIR_REMOTE,
                    len(runners))
        subprocess_utils.run_in_parallel(_sync, runners)

    @timeline.event
    def sync_file_mounts(self, handle: SliceResourceHandle,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        runners = handle.get_command_runners()
        log_path = os.path.join(_log_dir_for(handle.cluster_name),
                                'file_mounts.log')
        for dst, src in (all_file_mounts or {}).items():
            if src.startswith('gs://'):
                from skypilot_tpu.data import storage_mounting
                cmd = storage_mounting.copy_object_command(src, dst)
                subprocess_utils.run_in_parallel(
                    lambda r, c=cmd: r.run_or_raise(c, log_path=log_path),
                    runners)
            else:
                src_exp = os.path.expanduser(src)
                src_exp = (src_exp.rstrip('/') +
                           '/') if os.path.isdir(src_exp) else src_exp

                def _sync(runner, s=src_exp, d=dst):
                    runner.rsync(s, d, up=True, log_path=log_path)

                subprocess_utils.run_in_parallel(_sync, runners)
        for mount_path, storage in (storage_mounts or {}).items():
            from skypilot_tpu.data import storage_mounting
            storage_mounting.mount_storage(runners, mount_path, storage,
                                           log_path)

    # ---------------------------------------------------------------- setup

    @timeline.event
    def setup(self, handle: SliceResourceHandle, task,
              detach_setup: bool = False) -> None:
        if task.setup is None:
            return
        runners = handle.get_command_runners()
        log_dir = _log_dir_for(handle.cluster_name)
        script = _make_setup_script(task.setup, task.envs)
        info = handle.cluster_info()
        logger.info('%s Running setup on %d host(s).', ux.emph('[setup]'),
                    len(runners))

        def _setup_one(i: int) -> None:
            runner = runners[i]
            env = _cluster_env(info, i)
            log_path = os.path.join(log_dir, f'setup-{i}.log')
            runner.run(f'mkdir -p {_WORKDIR_REMOTE}', log_path=log_path)
            rc = runner.run(script, log_path=log_path,
                            stream_logs=(i == 0), env=env)
            if rc != 0:
                raise exceptions.CommandError(
                    rc, f'setup on host {i}',
                    f'Setup failed; see {log_path}')

        subprocess_utils.run_in_parallel(_setup_one,
                                         list(range(len(runners))))

    # -------------------------------------------------------------- execute

    @timeline.event
    def execute(self, handle: SliceResourceHandle, task, detach_run: bool,
                dryrun: bool = False) -> Optional[int]:
        if dryrun:
            logger.info('Dryrun: skipping execution.')
            return None
        if task.run is None:
            logger.info('No run command; nothing to execute.')
            return None
        if not isinstance(task.run, str):
            raise exceptions.NotSupportedError(
                'Callable task.run is only supported for local execution; '
                'use a command string for cluster jobs.')
        head = handle.head_runner()
        run_timestamp = common.get_run_timestamp()
        handle.run_timestamp = run_timestamp
        task_id = common.make_task_id(task.name)
        spec = {
            'envs': task.envs,
            'task_id': os.environ.get('SKYTPU_TASK_ID_OVERRIDE', task_id),
            'task_name': task.name,
        }
        log_path = os.path.join(_log_dir_for(handle.cluster_name),
                                'exec.log')
        # 1. register the job on the head host
        add_cmd = podlet_codegen.JobCodeGen.add_job(
            task.name or 'task', getpass.getuser(), run_timestamp, spec)
        rc, stdout, stderr = head.run(add_cmd, require_outputs=True,
                                      log_path=log_path)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet add_job',
                                          stderr[-800:])
        job_id = podlet_codegen.parse_result(stdout)['job_id']
        # 2. upload the run bundle
        run_script = _make_run_script(task.run, task.envs,
                                      bool(task.workdir))
        local_script = os.path.join(_log_dir_for(handle.cluster_name),
                                    f'run-{job_id}.sh')
        with open(local_script, 'w', encoding='utf-8') as f:
            f.write(run_script)
        head.rsync(local_script, f'~/.skytpu/jobs/{job_id}/run.sh', up=True,
                   log_path=log_path)
        # 3. queue it (podlet scheduler picks it up FIFO)
        queue_cmd = podlet_codegen.JobCodeGen.queue_job(job_id)
        rc, stdout, stderr = head.run(queue_cmd, require_outputs=True,
                                      log_path=log_path)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet queue_job',
                                          stderr[-800:])
        logger.info('%s Job %d submitted (cluster %r).', ux.ok('[job]'),
                    job_id, handle.cluster_name)
        state.update_last_use(handle.cluster_name)
        if not detach_run:
            self.tail_logs(handle, job_id)
        return job_id

    # ------------------------------------------------------------- job ops

    def tail_logs(self, handle: SliceResourceHandle,
                  job_id: Optional[int] = None, follow: bool = True) -> int:
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.tail_logs(job_id, follow=follow)
        return int(head.run(cmd, stream_logs=True, log_path='/dev/null'))

    def get_job_queue(self, handle: SliceResourceHandle) -> List[Dict]:
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.get_job_queue()
        rc, stdout, stderr = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet queue', stderr[-800:])
        return podlet_codegen.parse_result(stdout)

    def cancel_jobs(self, handle: SliceResourceHandle,
                    job_ids: Optional[List[int]] = None) -> List[int]:
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.cancel_jobs(job_ids)
        rc, stdout, stderr = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet cancel', stderr[-800:])
        return podlet_codegen.parse_result(stdout)['cancelled']

    def get_job_status(self, handle: SliceResourceHandle,
                       job_id: Optional[int] = None) -> Dict:
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.get_job_status(job_id)
        rc, stdout, stderr = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet status', stderr[-800:])
        return podlet_codegen.parse_result(stdout)

    def set_autostop(self, handle: SliceResourceHandle, idle_minutes: int,
                     down: bool = False) -> None:
        if (handle.launched_resources.is_tpu and idle_minutes >= 0 and
                not down):
            raise exceptions.NotSupportedError(
                'TPU slices cannot be stopped: use autostop with --down '
                '(autodown).')
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.set_autostop(idle_minutes, down)
        rc, stdout, stderr = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, 'podlet autostop',
                                          stderr[-800:])
        state.set_cluster_autostop(handle.cluster_name, idle_minutes, down)

    def sync_down_logs(self, handle: SliceResourceHandle,
                       job_id: Optional[int] = None,
                       local_dir: Optional[str] = None) -> str:
        """Copy a job's log tree from the head host to the local machine.
        Parity: sync_down_logs (cloud_vm_ray_backend.py:3630)."""
        status = self.get_job_status(handle, job_id)
        job_id = status['job_id']
        head = handle.head_runner()
        cmd = podlet_codegen.JobCodeGen.get_job_queue()
        rc, stdout, _ = head.run(cmd, require_outputs=True)
        jobs = podlet_codegen.parse_result(stdout)
        match = [j for j in jobs if j['job_id'] == job_id]
        if not match:
            raise exceptions.JobNotFoundError(f'job {job_id}')
        run_timestamp = match[0]['run_timestamp']
        local_dir = local_dir or os.path.join(common.logs_dir(),
                                              handle.cluster_name,
                                              run_timestamp)
        os.makedirs(local_dir, exist_ok=True)
        head.rsync(f'~/sky_logs/{run_timestamp}/', local_dir + '/', up=False)
        return local_dir

    # ------------------------------------------------------------- teardown

    @timeline.event
    def teardown(self, handle: SliceResourceHandle, terminate: bool,
                 purge: bool = False) -> None:
        cluster_name = handle.cluster_name
        if (not terminate and handle.launched_resources.is_tpu):
            raise exceptions.NotSupportedError(
                'TPU slices cannot be stopped (the ICI fabric allocation is '
                'released); use `skytpu down` to terminate.')
        with locks.cluster_status_lock(cluster_name):
            try:
                provisioner.teardown_cluster(handle.provider, cluster_name,
                                             terminate)
            except Exception as e:  # pylint: disable=broad-except
                if not purge:
                    raise
                logger.warning('Teardown error ignored due to purge: %s', e)
            state.remove_cluster(cluster_name, terminate=terminate)
            if terminate:
                ssh_config.remove_cluster(cluster_name)
        verb = 'Terminated' if terminate else 'Stopped'
        logger.info('%s %s cluster %r.', ux.ok('[down]'), verb, cluster_name)


def _cluster_env(info: ClusterInfo, rank: int) -> Dict[str, str]:
    ips = info.internal_ips()
    return {
        common.ENV_VAR_NODE_RANK: str(rank),
        common.ENV_VAR_NODE_IPS: '\n'.join(ips),
        common.ENV_VAR_NUM_NODES: str(len(ips)),
        common.ENV_VAR_NUM_CHIPS_PER_NODE: str(info.chips_per_host),
        common.ENV_VAR_CLUSTER_NAME: info.cluster_name,
    }


def _make_setup_script(setup: str, envs: Dict[str, str]) -> str:
    exports = '\n'.join(
        f'export {k}={subprocess_utils.quote(str(v))}'
        for k, v in envs.items())
    return textwrap.dedent(f"""\
        set -e
        cd {_WORKDIR_REMOTE} 2>/dev/null || cd ~
        {exports}
        {setup}
        """)


def _make_run_script(run: str, envs: Dict[str, str],
                     has_workdir: bool) -> str:
    """Parity: make_task_bash_script (sky/skylet/log_lib.py:256).
    Rank/coordinator env comes from the podlet driver at execution time;
    user envs are additionally baked into the script so it behaves the same
    when run by hand for debugging."""
    cd = f'cd {_WORKDIR_REMOTE}' if has_workdir else 'cd ~'
    exports = '\n'.join(
        f'export {k}={subprocess_utils.quote(str(v))}'
        for k, v in envs.items())
    return textwrap.dedent(f"""\
        #!/bin/bash
        source ~/.bashrc 2>/dev/null || true
        {cd}
        """) + exports + '\n' + run + '\n'
