"""Abstract backend lifecycle contract.

Parity: sky/backends/backend.py:24,30 — provision / sync_workdir /
sync_file_mounts / setup / execute / teardown, plus the pickled
ResourceHandle stored in the local state DB.
"""
from typing import Any, Dict, Generic, Optional, TypeVar


class ResourceHandle:
    """Opaque per-cluster record persisted in the state DB."""
    cluster_name: str

    def get_cluster_name(self) -> str:
        return self.cluster_name


_HandleT = TypeVar('_HandleT', bound=ResourceHandle)


class Backend(Generic[_HandleT]):
    NAME = 'backend'

    # Stage methods; each corresponds to an execution.Stage.
    def provision(self, task, to_provision, dryrun: bool,
                  stream_logs: bool, cluster_name: str,
                  retry_until_up: bool = False) -> Optional[_HandleT]:
        raise NotImplementedError

    def sync_workdir(self, handle: _HandleT, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: _HandleT,
                         all_file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def setup(self, handle: _HandleT, task, detach_setup: bool) -> None:
        raise NotImplementedError

    def execute(self, handle: _HandleT, task, detach_run: bool,
                dryrun: bool = False) -> Optional[int]:
        raise NotImplementedError

    def post_execute(self, handle: _HandleT, down: bool) -> None:
        del handle, down

    def teardown(self, handle: _HandleT, terminate: bool,
                 purge: bool = False) -> None:
        raise NotImplementedError
