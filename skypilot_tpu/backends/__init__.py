"""Execution backends (parity: sky/backends/)."""
from skypilot_tpu.backends.backend import Backend, ResourceHandle
from skypilot_tpu.backends.slice_backend import (SliceBackend,
                                                 SliceResourceHandle)

__all__ = ['Backend', 'ResourceHandle', 'SliceBackend', 'SliceResourceHandle']
