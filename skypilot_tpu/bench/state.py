"""Benchmark-local state: SQLite DB at ``$SKYTPU_HOME/benchmark.db``.

Parity: sky/benchmark/benchmark_state.py — one row per benchmark plus one
row per (benchmark, candidate cluster) with the parsed callback summary
and derived cost/time estimates.
"""
import enum
import os
import pickle
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common

_local = threading.local()


class BenchmarkStatus(enum.Enum):
    INIT = 'INIT'
    RUNNING = 'RUNNING'
    FINISHED = 'FINISHED'
    TERMINATED = 'TERMINATED'


_CREATE_SQL = """\
CREATE TABLE IF NOT EXISTS benchmark (
    name TEXT PRIMARY KEY,
    task_name TEXT,
    launched_at INTEGER,
    status TEXT);
CREATE TABLE IF NOT EXISTS benchmark_results (
    benchmark TEXT,
    cluster TEXT,
    resources BLOB,
    num_nodes INTEGER,
    status TEXT,
    num_steps INTEGER,
    seconds_per_step REAL,
    init_seconds REAL,
    estimated_total_seconds REAL,
    estimated_cost REAL,
    updated_at INTEGER,
    PRIMARY KEY (benchmark, cluster));
"""


def _db() -> sqlite3.Connection:
    conn = getattr(_local, 'conn', None)
    path = os.path.join(common.home_dir(), 'benchmark.db')
    if conn is None or getattr(_local, 'path', None) != path:
        os.makedirs(common.home_dir(), exist_ok=True)
        conn = sqlite3.connect(path)
        conn.executescript(_CREATE_SQL)
        conn.row_factory = sqlite3.Row
        _local.conn = conn
        _local.path = path
    return conn


def add_benchmark(name: str, task_name: Optional[str]) -> None:
    with _db() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmark VALUES (?, ?, ?, ?)',
            (name, task_name, int(time.time()), BenchmarkStatus.INIT.value))


def set_benchmark_status(name: str, status: BenchmarkStatus) -> None:
    with _db() as conn:
        conn.execute('UPDATE benchmark SET status = ? WHERE name = ?',
                     (status.value, name))


def get_benchmark(name: str) -> Optional[Dict[str, Any]]:
    row = _db().execute('SELECT * FROM benchmark WHERE name = ?',
                        (name,)).fetchone()
    return dict(row) if row else None


def get_benchmarks() -> List[Dict[str, Any]]:
    return [dict(r) for r in _db().execute(
        'SELECT * FROM benchmark ORDER BY launched_at').fetchall()]


def delete_benchmark(name: str) -> None:
    with _db() as conn:
        conn.execute('DELETE FROM benchmark_results WHERE benchmark = ?',
                     (name,))
        conn.execute('DELETE FROM benchmark WHERE name = ?', (name,))


def add_result(benchmark: str, cluster: str, resources: Any,
               num_nodes: int) -> None:
    with _db() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmark_results '
            '(benchmark, cluster, resources, num_nodes, status, updated_at) '
            'VALUES (?, ?, ?, ?, ?, ?)',
            (benchmark, cluster, pickle.dumps(resources), num_nodes,
             BenchmarkStatus.INIT.value, int(time.time())))


def update_result(benchmark: str, cluster: str, *, status: BenchmarkStatus,
                  num_steps: Optional[int] = None,
                  seconds_per_step: Optional[float] = None,
                  init_seconds: Optional[float] = None,
                  estimated_total_seconds: Optional[float] = None,
                  estimated_cost: Optional[float] = None) -> None:
    with _db() as conn:
        conn.execute(
            'UPDATE benchmark_results SET status = ?, '
            'num_steps = COALESCE(?, num_steps), '
            'seconds_per_step = COALESCE(?, seconds_per_step), '
            'init_seconds = COALESCE(?, init_seconds), '
            'estimated_total_seconds = COALESCE(?, estimated_total_seconds), '
            'estimated_cost = COALESCE(?, estimated_cost), '
            'updated_at = ? WHERE benchmark = ? AND cluster = ?',
            (status.value, num_steps, seconds_per_step, init_seconds,
             estimated_total_seconds, estimated_cost, int(time.time()),
             benchmark, cluster))


def get_results(benchmark: str) -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT * FROM benchmark_results WHERE benchmark = ? '
        'ORDER BY cluster', (benchmark,)).fetchall()
    out = []
    for r in rows:
        d = dict(r)
        d['resources'] = pickle.loads(d['resources'])
        out.append(d)
    return out


def reset_for_tests() -> None:
    if getattr(_local, 'conn', None) is not None:
        _local.conn.close()
        _local.conn = None
        _local.path = None
