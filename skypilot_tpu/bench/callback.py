"""User-side benchmark callback: per-step timestamps for `skytpu bench`.

Parity: the SkyCallback library (sky/callbacks/sky_callback/base.py:20) —
a tiny, dependency-free timer the training loop calls once per step; it
periodically writes an atomic ``summary.json`` that the bench harness
syncs down and turns into $/step and time-to-completion estimates.

STDLIB-ONLY by design: job hosts run this inside arbitrary user programs
(it is also loadable by file path, without importing the skypilot_tpu
package).  In multi-host jobs only rank 0 writes (``SKYTPU_NODE_RANK``).

Usage::

    from skypilot_tpu.bench import BenchmarkCallback
    cb = BenchmarkCallback(total_steps=1000)
    for batch in data:
        cb.on_step_begin()
        step(batch)
        cb.on_step_end()

or wrap the iterable::

    for batch in step_iterator(data, total_steps=1000):
        step(batch)
"""
import json
import os
import time

ENV_LOG_DIR = 'SKYTPU_BENCHMARK_LOG_DIR'
SUMMARY_NAME = 'summary.json'
_BOOT_TIME = time.time()  # import time ~ program start


def default_log_dir() -> str:
    return os.environ.get(
        ENV_LOG_DIR, os.path.join('~', '.skytpu', 'benchmark_logs',
                                  'default'))


class BenchmarkCallback:
    """Records step timestamps; rank 0 writes summary.json periodically."""

    def __init__(self, log_dir=None, total_steps=None, warmup_steps=1,
                 write_every=10):
        self.log_dir = os.path.expanduser(log_dir or default_log_dir())
        self.total_steps = total_steps
        self.warmup_steps = warmup_steps
        self.write_every = max(1, write_every)
        self.create_time = time.time()
        self.first_step_time = None
        self.warmup_end_time = None
        self.last_step_time = None
        self.num_steps = 0
        self._is_writer = os.environ.get('SKYTPU_NODE_RANK', '0') == '0'
        if self._is_writer:
            os.makedirs(self.log_dir, exist_ok=True)

    def on_step_begin(self):
        if self.first_step_time is None:
            self.first_step_time = time.time()

    def on_step_end(self):
        now = time.time()
        if self.first_step_time is None:  # begin() not called: tolerate
            self.first_step_time = now
        self.num_steps += 1
        self.last_step_time = now
        if self.num_steps == self.warmup_steps:
            self.warmup_end_time = now
        if self.num_steps % self.write_every == 0:
            self.write_summary()

    def summary(self) -> dict:
        return {
            'boot_time': _BOOT_TIME,
            'create_time': self.create_time,
            'first_step_time': self.first_step_time,
            'warmup_end_time': self.warmup_end_time,
            'last_step_time': self.last_step_time,
            'num_steps': self.num_steps,
            'warmup_steps': self.warmup_steps,
            'total_steps': self.total_steps,
        }

    def write_summary(self):
        if not self._is_writer:
            return
        path = os.path.join(self.log_dir, SUMMARY_NAME)
        tmp = f'{path}.tmp.{os.getpid()}'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(self.summary(), f)
        os.replace(tmp, path)  # atomic: the harness may rsync mid-write

    # Context-manager form: `with BenchmarkCallback(...) as cb:` flushes the
    # final partial window on exit.
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.write_summary()
        return False


def step_iterator(iterable, log_dir=None, total_steps=None, warmup_steps=1,
                  write_every=10):
    """Wrap a step iterable; timestamps each yielded item as one step."""
    with BenchmarkCallback(log_dir=log_dir, total_steps=total_steps,
                           warmup_steps=warmup_steps,
                           write_every=write_every) as cb:
        for item in iterable:
            cb.on_step_begin()
            yield item
            cb.on_step_end()
