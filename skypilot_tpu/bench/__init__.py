"""Cost benchmarking: run one task on N candidate resources, compare
$/step and time-to-completion.  Parity: sky/benchmark/ + sky/callbacks/.
"""
from skypilot_tpu.bench.callback import BenchmarkCallback, step_iterator
from skypilot_tpu.bench.state import BenchmarkStatus
from skypilot_tpu.bench.utils import (delete_benchmark,
                                      down_benchmark_clusters,
                                      launch_benchmark,
                                      update_benchmark_state)

__all__ = [
    'BenchmarkCallback',
    'BenchmarkStatus',
    'delete_benchmark',
    'down_benchmark_clusters',
    'launch_benchmark',
    'step_iterator',
    'update_benchmark_state',
]
