"""Benchmark orchestration: launch candidates, harvest summaries, report.

Parity: sky/benchmark/benchmark_utils.py:432,488 — launch the same task on
N candidate resources in parallel, pull the callback's summary.json from
each cluster, and derive seconds/step, time- and cost-to-completion.
"""
import copy
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import usage
from skypilot_tpu import exceptions, logsys
from skypilot_tpu.bench import callback as callback_lib
from skypilot_tpu.bench import state as bench_state
from skypilot_tpu.bench.state import BenchmarkStatus
from skypilot_tpu.utils import common, subprocess_utils

logger = logsys.init_logger(__name__)

_CLUSTER_PREFIX = 'skytpu-bench-'
# Where the callback writes on the cluster (exported to the job env).
_REMOTE_LOG_DIR = '~/.skytpu/benchmark_logs'


def cluster_name(benchmark: str, index: int) -> str:
    return f'{_CLUSTER_PREFIX}{benchmark}-{index}'


@usage.entrypoint('bench.launch')
def launch_benchmark(benchmark: str, task: 'Any',
                     candidates: List['Any'],
                     detach: bool = True) -> List[str]:
    """Launch `task` once per candidate Resources, in parallel.

    Returns the launched cluster names.  Each launch exports
    SKYTPU_BENCHMARK_LOG_DIR so BenchmarkCallback lands in a known place.
    """
    from skypilot_tpu import execution
    if bench_state.get_benchmark(benchmark) is not None:
        raise exceptions.SkyTpuError(
            f'Benchmark {benchmark!r} already exists. '
            f'`skytpu bench delete {benchmark}` first.')
    bench_state.add_benchmark(benchmark, task.name)
    names = [cluster_name(benchmark, i) for i in range(len(candidates))]

    def _launch_one(i: int) -> Optional[str]:
        t = copy.deepcopy(task)
        t.set_resources(candidates[i])
        t.update_envs({
            callback_lib.ENV_LOG_DIR: f'{_REMOTE_LOG_DIR}/{benchmark}',
        })
        bench_state.add_result(benchmark, names[i], candidates[i],
                               t.num_nodes or 1)
        try:
            execution.launch(t, cluster_name=names[i], detach_run=detach,
                             stream_logs=False)
            bench_state.update_result(benchmark, names[i],
                                      status=BenchmarkStatus.RUNNING)
            return names[i]
        except Exception as e:  # pylint: disable=broad-except
            logger.error('bench launch %s failed: %s', names[i], e)
            bench_state.update_result(benchmark, names[i],
                                      status=BenchmarkStatus.TERMINATED)
            return None

    launched = [n for n in subprocess_utils.run_in_parallel(
        _launch_one, list(range(len(candidates)))) if n]
    # A benchmark with zero surviving candidates never ran: record that
    # instead of letting the all-terminal rollup report it FINISHED.
    bench_state.set_benchmark_status(
        benchmark,
        BenchmarkStatus.RUNNING if launched else BenchmarkStatus.TERMINATED)
    return launched


def _parse_summary(raw: Dict[str, Any], resources: 'Any',
                   num_nodes: int) -> Dict[str, Optional[float]]:
    """Derive the report row from a callback summary dict."""
    num_steps = raw.get('num_steps') or 0
    warmup = raw.get('warmup_steps') or 0
    first = raw.get('first_step_time')
    warmup_end = raw.get('warmup_end_time')
    last = raw.get('last_step_time')
    boot = raw.get('boot_time')
    total_steps = raw.get('total_steps')
    out: Dict[str, Optional[float]] = {
        'num_steps': num_steps,
        'seconds_per_step': None,
        'init_seconds': None,
        'estimated_total_seconds': None,
        'estimated_cost': None,
    }
    if boot is not None and first is not None:
        out['init_seconds'] = first - boot
    # Steady-state rate excludes warmup steps (compile time on TPU).
    if (last is not None and warmup_end is not None and
            num_steps > warmup > 0 and last > warmup_end):
        out['seconds_per_step'] = (last - warmup_end) / (num_steps - warmup)
    elif last is not None and first is not None and num_steps > 1:
        out['seconds_per_step'] = (last - first) / num_steps
    sps = out['seconds_per_step']
    # get_cost prices the WHOLE slice; num_nodes (gang width, i.e. slice
    # count) is the only multiplier — parity with core.cost_report.
    if sps is not None and total_steps:
        est = (out['init_seconds'] or 0.0) + sps * total_steps
        out['estimated_total_seconds'] = est
        try:
            out['estimated_cost'] = resources.get_cost(est) * num_nodes
        except exceptions.SkyTpuError:
            out['estimated_cost'] = None
    elif sps is not None and last is not None and boot is not None:
        # No declared total: report cost of the observed run so far.
        try:
            out['estimated_cost'] = (resources.get_cost(last - boot) *
                                     num_nodes)
        except exceptions.SkyTpuError:
            out['estimated_cost'] = None
    return out


def update_benchmark_state(benchmark: str) -> List[Dict[str, Any]]:
    """Pull summary.json from each candidate cluster and refresh results."""
    from skypilot_tpu import backend_utils
    from skypilot_tpu.backends.slice_backend import SliceBackend
    rows = bench_state.get_results(benchmark)

    def _update_one(row: Dict[str, Any]) -> None:
        cname = row['cluster']
        if row['status'] == BenchmarkStatus.TERMINATED.value:
            return
        try:
            handle = backend_utils.check_cluster_available(cname)
        except exceptions.ClusterDoesNotExist:
            bench_state.update_result(benchmark, cname,
                                      status=BenchmarkStatus.TERMINATED)
            return
        except exceptions.SkyTpuError:
            # Transiently not UP (INIT, locked refresh, …): keep the row
            # as-is and try again on the next `bench show`.
            return
        local_dir = os.path.join(common.home_dir(), 'benchmark_logs',
                                 benchmark, cname)
        os.makedirs(local_dir, exist_ok=True)
        head = handle.head_runner()
        remote = f'{_REMOTE_LOG_DIR}/{benchmark}/{callback_lib.SUMMARY_NAME}'
        try:
            head.rsync(remote, os.path.join(local_dir,
                                            callback_lib.SUMMARY_NAME),
                       up=False)
        except exceptions.SkyTpuError:
            return  # no summary yet
        path = os.path.join(local_dir, callback_lib.SUMMARY_NAME)
        if not os.path.exists(path):
            return
        with open(path, 'r', encoding='utf-8') as f:
            raw = json.load(f)
        derived = _parse_summary(raw, row['resources'], row['num_nodes'])
        status = BenchmarkStatus.RUNNING
        try:
            from skypilot_tpu.podlet import job_lib
            job = SliceBackend().get_job_status(handle, None)
            if (job and job.get('status') and
                    job_lib.JobStatus(job['status']).is_terminal()):
                status = BenchmarkStatus.FINISHED
        except (exceptions.SkyTpuError, ValueError):
            pass
        bench_state.update_result(benchmark, cname, status=status, **derived)

    subprocess_utils.run_in_parallel(_update_one, rows)
    new_rows = bench_state.get_results(benchmark)
    if new_rows and all(r['status'] in (BenchmarkStatus.FINISHED.value,
                                        BenchmarkStatus.TERMINATED.value)
                        for r in new_rows):
        bench_state.set_benchmark_status(benchmark, BenchmarkStatus.FINISHED)
    return new_rows


def down_benchmark_clusters(benchmark: str) -> None:
    from skypilot_tpu import core

    def _down(row: Dict[str, Any]) -> None:
        try:
            core.down(row['cluster'])
        except exceptions.SkyTpuError as e:
            logger.warning('bench down %s: %s', row['cluster'], e)

    subprocess_utils.run_in_parallel(_down, bench_state.get_results(benchmark))
    bench_state.set_benchmark_status(benchmark, BenchmarkStatus.TERMINATED)


def delete_benchmark(benchmark: str) -> None:
    if bench_state.get_benchmark(benchmark) is None:
        raise exceptions.SkyTpuError(f'Benchmark {benchmark!r} not found.')
    bench_state.delete_benchmark(benchmark)
