"""Radix tree over the paged KV block pool: automatic prefix caching.

One node per ``kv_block_size``-token run: a node's path from its
adapter's root spells a block-aligned token prefix, and the node holds
ONE pool block id whose rows carry that run's KV (written by whichever
prefill produced them).  The tree itself owns one refcount on every
block it holds — engine slots that match a prefix bump the same blocks'
refcounts through ``_append_shared_blocks``, so sharing is the pool's
ordinary refcount discipline, with the tree acting as one more holder.

The tree is a pure host-side index (dicts of python ints): it never
touches the device.  All refcount side effects run through callbacks
supplied by the engine's allocator, so the accounting lives in exactly
one place (engine.py).  Parity: vLLM automatic-prefix-caching block
hashing / SGLang RadixAttention, restricted to block granularity.

Concurrency: every mutating call happens under the engine lock.  The
``generation`` counter bumps on :meth:`clear` so a caller that matched
against a pre-quarantine tree can detect (and must not use) stale
block ids — see ``_start_radix_group_paged``.
"""
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

Run = Tuple[int, ...]


class _Node:
    __slots__ = ('run', 'block', 'children', 'parent', 'holder',
                 'last_used', 'pinned')

    def __init__(self, run: Run, block: int, parent: Optional['_Node'],
                 holder: Dict[Run, '_Node'], last_used: int):
        self.run = run
        self.block = block
        self.children: Dict[Run, '_Node'] = {}
        self.parent = parent
        self.holder = holder          # the dict that maps run -> self
        self.last_used = last_used
        self.pinned = False


class RadixTree:
    """Block-granular prefix index.  See the module docstring."""

    def __init__(self, block_size: int):
        if block_size < 1:
            raise ValueError(f'block_size must be >= 1 ({block_size})')
        self.block_size = block_size
        # Per-adapter roots: prefix KV is adapter-dependent, so entries
        # only ever match requests naming the same adapter (None = base
        # model) — the same gate the registered-prefix store applies.
        self._roots: Dict[Optional[str], Dict[Run, _Node]] = {}
        self._clock = 0               # monotonic LRU counter
        self._nodes = 0
        self._pinned = 0
        self.generation = 0

    # ------------------------------------------------------------ stats

    @property
    def nodes(self) -> int:
        return self._nodes

    @property
    def blocks_held(self) -> int:
        # One block per node, exactly.
        return self._nodes

    @property
    def pinned(self) -> int:
        return self._pinned

    @property
    def clock(self) -> int:
        """Current LRU tick — compared against a node's ``last_used``
        to judge recency (the host-tier spill gate)."""
        return self._clock

    def walk(self) -> Iterator[_Node]:
        for level in self._roots.values():
            stack = list(level.values())
            while stack:
                nd = stack.pop()
                yield nd
                stack.extend(nd.children.values())

    def walk_adapters(self) -> Iterator[Tuple[Optional[str], _Node]]:
        """walk() with adapter identity — the per-root DFS loses which
        root it started from, which the host tier (keys carry the
        adapter) and hot-set export need back."""
        for adapter, level in self._roots.items():
            stack = list(level.values())
            while stack:
                nd = stack.pop()
                yield adapter, nd
                stack.extend(nd.children.values())

    @staticmethod
    def path_tokens(node: _Node) -> Tuple[int, ...]:
        """The full token prefix a node's path spells (root run first)
        — the node's topology-neutral identity for the host tier."""
        runs = []
        nd: Optional[_Node] = node
        while nd is not None:
            runs.append(nd.run)
            nd = nd.parent
        out: List[int] = []
        for run in reversed(runs):
            out.extend(run)
        return tuple(out)

    # ------------------------------------------------------- operations

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def clear(self) -> None:
        """Drop every node WITHOUT touching refcounts — the quarantine
        path resets the whole allocator wholesale, so per-block derefs
        would double-count.  Bumps ``generation`` (stale-match guard)."""
        self._roots = {}
        self._nodes = 0
        self._pinned = 0
        self.generation += 1

    def match(self, adapter: Optional[str], tokens: Sequence[int],
              max_tokens: int) -> List[int]:
        """Longest cached block-aligned prefix of ``tokens`` under
        ``adapter``, capped at ``max_tokens`` tokens.  Returns the
        matched nodes' block ids in path order (possibly empty) and
        LRU-touches the whole path.  The caller must bump each block's
        refcount (under the same lock) before the ids can outlive the
        next eviction."""
        bs = self.block_size
        level = self._roots.get(adapter)
        limit = min(len(tokens), max_tokens) // bs
        out: List[int] = []
        if not level or limit < 1:
            return out
        now = self._tick()
        for i in range(limit):
            run = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            node = level.get(run)
            if node is None:
                break
            node.last_used = now
            out.append(node.block)
            level = node.children
        return out

    def peek(self, adapter: Optional[str], tokens: Sequence[int],
             max_tokens: int) -> List[int]:
        """match() without the LRU touch or clock tick: a read-only
        probe for hot-set export/adoption, which must not reshuffle
        recency while iterating candidates."""
        bs = self.block_size
        level = self._roots.get(adapter)
        limit = min(len(tokens), max_tokens) // bs
        out: List[int] = []
        if not level or limit < 1:
            return out
        for i in range(limit):
            run = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            node = level.get(run)
            if node is None:
                break
            out.append(node.block)
            level = node.children
        return out

    def insert(self, adapter: Optional[str], tokens: Sequence[int],
               blocks: Sequence[int],
               addref: Callable[[int], None],
               deref: Optional[Callable[[int], None]] = None,
               own: bool = False, pinned: bool = False) -> int:
        """Index ``blocks[i]`` as the node for the i-th token run.
        Idempotent on the already-cached part of the path: an existing
        node keeps ITS block, the caller's duplicate is left alone
        (``own=False`` — the caller's slot still holds its own ref) or
        dereffed (``own=True`` — the caller transfers ownership, so a
        duplicate must not leak).  Newly adopted blocks get ``addref``
        under ``own=False``; under ``own=True`` the tree takes over the
        caller's existing ref.  Returns the number of nodes created."""
        bs = self.block_size
        level = self._roots.setdefault(adapter, {})
        parent: Optional[_Node] = None
        now = self._tick()
        created = 0
        for i, blk in enumerate(blocks):
            run = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            if len(run) < bs:
                break                    # partial tail run: not indexable
            node = level.get(run)
            if node is None:
                node = _Node(run, int(blk), parent, level, now)
                level[run] = node
                self._nodes += 1
                created += 1
                if not own:
                    addref(int(blk))
            else:
                node.last_used = now
                if own and int(blk) != node.block:
                    assert deref is not None
                    deref(int(blk))      # duplicate of a cached run
            if pinned and not node.pinned:
                node.pinned = True
                self._pinned += 1
            parent = node
            level = node.children
        return created

    def evict(self, need: int, block_refs,
              deref: Callable[[int], None],
              on_evict: Optional[Callable[[Optional[str], _Node],
                                          None]] = None) -> int:
        """Free up to ``need`` blocks by deleting unpinned LEAF nodes
        whose block refcount is exactly 1 (the tree holds the only
        reference, so the deref actually frees a block), LRU-first.
        Cascades: a parent becomes an eligible leaf once its children
        are gone.  Returns the number of blocks freed.

        ``on_evict(adapter, node)`` fires BEFORE the deref, while the
        victim's block rows are still the prefix's — the engine's
        host-tier spill hook snapshots them there.  The callback must
        not mutate the tree."""
        freed = 0
        while freed < need:
            victim: Optional[_Node] = None
            victim_adapter: Optional[str] = None
            for adapter, nd in self.walk_adapters():
                if nd.children or nd.pinned:
                    continue
                if block_refs[nd.block] != 1:
                    continue             # a slot still shares it
                if victim is None or nd.last_used < victim.last_used:
                    victim = nd
                    victim_adapter = adapter
            if victim is None:
                return freed
            if on_evict is not None:
                on_evict(victim_adapter, victim)
            # holder is the parent's children dict (or an adapter
            # root), so this single delete detaches the node.
            del victim.holder[victim.run]
            self._nodes -= 1
            deref(victim.block)
            freed += 1
        return freed
