"""TPU-native inference engine (JetStream-analog serving runtime).

The reference serves LLMs by launching external engines (vLLM on GPUs,
JetStream on TPUs — examples/tpu/v6e/serve-llama2-7b.yaml); here the
engine is part of the framework: slotted KV cache, bucketed prefill,
jitted single-token decode over the whole batch, continuous batching.
Fault tolerance is part of the surface too: deterministic fault
injection (faults.FaultPlan), step-level failure containment, and
per-request deadlines (Request.deadline_s).
"""
from skypilot_tpu.infer.engine import (InferConfig, InferenceEngine,
                                       Request, RequestResult,
                                       resolve_cache_dtype)
from skypilot_tpu.infer.faults import FaultPlan, FaultSpec, InjectedFault

__all__ = ['InferConfig', 'InferenceEngine', 'Request', 'RequestResult',
           'resolve_cache_dtype', 'FaultPlan', 'FaultSpec',
           'InjectedFault']
