"""Paged KV block pool: allocator, refcounts, geometry, host tier.

Extracted from engine.py (ROADMAP item 6's decomposition): the engine
keeps the public `InferenceEngine` surface and the scheduling logic;
this module owns the host-side pool bookkeeping —

- :class:`BlockPool`: per-block refcounts, the free list, per-slot
  block tables and the pool geometry (block size / count).  Pure
  host-side numpy + python ints; it never touches the device.  All
  methods run under the ENGINE lock (the pool has no lock of its own —
  the engine's `_lock` already serializes every allocator call with
  the dispatch path, and a second lock would only add ordering
  hazards).
- :class:`HostKVTier`: the second tier of the pool.  When radix
  eviction would free a recently-referenced node's block, the engine
  snapshots the block's rows to host RAM here (asynchronously, via
  ``copy_to_host_async``) before the block id is recycled; the next
  radix match restores the rows into fresh pool blocks with
  ``jax.device_put`` overlapped with the suffix prefill.  Entries are
  keyed by ``(adapter, token-prefix)`` — the TOPOLOGY-NEUTRAL form:
  rows are stored as the global ``[L, Hkv, block_size, D]`` array
  (gathered across chips on spill), so a block spilled from a tp=2
  replica restores onto tp=1 or tp=4 unchanged.

Refcount discipline is unchanged by the tier: a spill COPIES rows (the
block is still freed by the ordinary eviction deref), and a restore
allocates fresh blocks through the ordinary allocator — so the
``SKYTPU_BLOCK_SANITIZER`` conservation law holds with the tier on.
"""
import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Host-tier key: (adapter, token-prefix) for the block's FULL path from
# the radix root — the same identity the radix tree gives the block, so
# a restore can only ever resurrect rows for exactly the prefix that
# produced them.
TierKey = Tuple[Optional[str], Tuple[int, ...]]


class BlockPool:
    """Host-side allocator for the paged KV cache.  See the module
    docstring; every method is called under the engine lock."""

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks: int, num_slots: int):
        self._num_blocks = num_blocks
        self.block_size = block_size
        # Blocks a single full-length request spans (table width).
        self._max_blocks = max_blocks
        # Refcounts per block (dump block 0 is permanently held), the
        # free list, and per-slot block tables (+ allocated counts).
        # Shared prefix blocks simply carry refcount > 1; freeing a
        # slot decrefs every table entry.
        self._block_refs = np.zeros((num_blocks,), np.int32)  # guarded-by: engine _lock
        self._tables_np = np.zeros((num_slots, max_blocks), np.int32)  # guarded-by: engine _lock
        self._slot_nblocks = np.zeros((num_slots,), np.int32)  # guarded-by: engine _lock
        self._free_blocks: List[int] = []  # guarded-by: engine _lock
        self.reset()

    def reset(self) -> None:  # locked: engine
        """Empty allocator: every block free except the reserved dump
        block 0 (the quarantine path rebuilds the device pool and
        resets this bookkeeping wholesale)."""
        self._block_refs[:] = 0
        self._block_refs[0] = 1
        self._free_blocks = list(range(self._num_blocks - 1, 0, -1))
        self._tables_np[:] = 0
        self._slot_nblocks[:] = 0

    def _alloc_blocks(self, k: int) -> List[int]:  # locked: engine
        if k > len(self._free_blocks):
            # Admission control reserves worst-case demand up front, so
            # a running slot can never get here; reaching it means the
            # accounting is broken.
            raise RuntimeError(
                f'KV block pool exhausted: need {k}, have '
                f'{len(self._free_blocks)} free (admission accounting '
                'bug)')
        out = [self._free_blocks.pop() for _ in range(k)]
        for b in out:
            self._block_refs[b] = 1
        return out

    def _deref_block(self, b: int) -> None:  # locked: engine
        if b == 0:
            return
        self._block_refs[b] -= 1
        if self._block_refs[b] == 0:
            self._free_blocks.append(b)

    def _addref_block(self, b: int) -> None:  # locked: engine
        """Refcount bump for a holder OTHER than a slot table (the
        radix tree adopting a finishing slot's prompt blocks)."""
        self._block_refs[b] += 1

    def _ensure_blocks(self, slot: int, upto: int) -> None:  # locked: engine
        """Grow the slot's table with fresh private blocks so rows
        [0, upto) are resident (no-op when already covered)."""
        need = min(-(-upto // self.block_size), self._max_blocks)
        cur = int(self._slot_nblocks[slot])
        if need <= cur:
            return
        ids = self._alloc_blocks(need - cur)  # owns-blocks: table
        self._tables_np[slot, cur:need] = ids
        self._slot_nblocks[slot] = need

    def _append_shared_blocks(self, slot: int,  # locked: engine
                              ids: Sequence[int]) -> None:
        """Append a prefix's full blocks to the slot's table by
        REFERENCE (refcount bump) — the copy-free prefix hit."""
        cur = int(self._slot_nblocks[slot])
        self._tables_np[slot, cur:cur + len(ids)] = ids
        for b in ids:
            self._block_refs[b] += 1
        self._slot_nblocks[slot] = cur + len(ids)

    def _free_slot_blocks(self, slot: int) -> None:  # locked: engine
        n = int(self._slot_nblocks[slot])
        for b in self._tables_np[slot, :n]:
            self._deref_block(int(b))
        self._tables_np[slot, :] = 0
        self._slot_nblocks[slot] = 0


class HostKVTier:
    """Bounded host-RAM LRU of spilled KV blocks.  See the module
    docstring; every method is called under the engine lock.

    Spills are ASYNC: :meth:`spill` only kicks off per-layer
    ``copy_to_host_async`` transfers and parks the device handles on a
    pending list — the blocking ``np.asarray`` gather (a no-op once
    the async copy landed) happens in :meth:`finalize`, which runs at
    the next probe/export/idle-quiesce, never on the eviction path.
    The device slices are fresh buffers (snapshotted before the block
    id is recycled), so a later pool-donating dispatch cannot
    invalidate them.
    """

    def __init__(self, budget_bytes: int, block_size: int,
                 recency_window: int = 0):
        self.budget_bytes = int(budget_bytes)
        self.block_size = block_size
        # Clock-tick window for "recently referenced": an evicted node
        # older than this is dead-cold traffic not worth the copy.
        self.recency_window = int(recency_window)
        # key -> (k_rows, v_rows), each np [L, Hkv, block_size, D] in
        # cache dtype; insertion order == LRU order (oldest first).
        self._entries: 'collections.OrderedDict[TierKey, Tuple[np.ndarray, np.ndarray]]' = (
            collections.OrderedDict())  # guarded-by: engine _lock
        # (key, [k_dev per layer], [v_dev per layer]) copies in flight.
        self._pending: List[Tuple[TierKey, list, list]] = []  # guarded-by: engine _lock
        self._bytes = 0
        self.stats = {'spills': 0, 'restores': 0, 'lookups': 0,  # guarded-by: engine _lock
                      'hits': 0, 'evictions': 0, 'dropped': 0}

    # ------------------------------------------------------------- state

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def entries(self) -> int:
        return len(self._entries)

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    def clear(self) -> None:  # locked: engine
        self._entries.clear()
        self._pending.clear()
        self._bytes = 0

    # ------------------------------------------------------------- spill

    def spill(self, key: TierKey, k_slices: list, v_slices: list) -> None:  # locked: engine
        """Enqueue one block's per-layer device row slices for async
        host copy.  Non-blocking: the transfer streams while the chips
        keep serving; finalize() lands it."""
        for x in k_slices:
            x.copy_to_host_async()
        for x in v_slices:
            x.copy_to_host_async()
        self._pending.append((key, k_slices, v_slices))
        self.stats['spills'] += 1

    def finalize(self) -> None:  # locked: engine
        """Land in-flight spills into the LRU map and trim to budget.
        np.asarray blocks only until the already-started async copy
        completes."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for key, ks, vs in pending:
            k_rows = np.stack([np.asarray(x) for x in ks])
            v_rows = np.stack([np.asarray(x) for x in vs])
            nbytes = k_rows.nbytes + v_rows.nbytes
            if nbytes > self.budget_bytes:
                self.stats['dropped'] += 1
                continue
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[0].nbytes + old[1].nbytes
            self._entries[key] = (k_rows, v_rows)
            self._bytes += nbytes
        while self._bytes > self.budget_bytes and self._entries:
            _, (k_rows, v_rows) = self._entries.popitem(last=False)
            self._bytes -= k_rows.nbytes + v_rows.nbytes
            self.stats['evictions'] += 1

    # ----------------------------------------------------------- restore

    def contains(self, key: TierKey) -> bool:  # locked: engine
        """Restore probe (counts toward the restore-hit rate)."""
        self.finalize()
        self.stats['lookups'] += 1
        if key in self._entries:
            self.stats['hits'] += 1
            return True
        return False

    def take(self, key: TierKey) -> Optional[Tuple[np.ndarray, np.ndarray]]:  # locked: engine
        """Pop an entry for restore (the rows move back into pool
        blocks, so keeping the host copy would just double-count the
        budget)."""
        self.finalize()
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._bytes -= entry[0].nbytes + entry[1].nbytes
        return entry

    def get(self, key: TierKey) -> Optional[Tuple[np.ndarray, np.ndarray]]:  # locked: engine
        """Non-destructive read (hot-set export): LRU-touches."""
        self.finalize()
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def keys_recent_first(self) -> List[TierKey]:  # locked: engine
        self.finalize()
        return list(reversed(self._entries))

    # ------------------------------------------------------------- audit

    def audit(self) -> List[str]:  # locked: engine
        """Conservation-style self-check for the block sanitizer: the
        byte ledger must equal the entries it claims to cover, and the
        budget bound must hold.  Returns error strings (empty = ok)."""
        self.finalize()
        errors = []
        actual = sum(k.nbytes + v.nbytes
                     for k, v in self._entries.values())
        if actual != self._bytes:
            errors.append(
                f'host tier byte ledger {self._bytes} != entry bytes '
                f'{actual} (leak across the tier boundary)')
        if self._bytes > self.budget_bytes:
            errors.append(
                f'host tier over budget: {self._bytes} > '
                f'{self.budget_bytes}')
        return errors

    def stats_section(self) -> Dict[str, Any]:
        """kv.host_tier rows (key set mirrored by the engine's
        disabled-tier branch — wire-contract branch stability).  Read
        LOCK-FREE from kv_health()/stats() like the other counters, so
        no finalize here: in-flight copies report as in_flight."""
        st = self.stats
        lookups = st['lookups']
        return {
            'enabled': True,
            'budget_bytes': self.budget_bytes,
            'bytes': self._bytes,
            'entries': len(self._entries),
            'spills': st['spills'],
            'restores': st['restores'],
            'restore_hit_rate': (st['hits'] / lookups) if lookups else 0.0,
            'in_flight': len(self._pending),
            'evictions': st['evictions'],
        }
