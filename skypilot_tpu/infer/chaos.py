"""Process-level chaos harness: a killable multi-replica fleet + LB.

The engine-level fault sites (`faults.py`) prove per-request
containment INSIDE one process.  This module proves the layer above —
the replica plane: N in-process replicas behind the real
`SkyTpuLoadBalancer`, with a seeded killer thread that consults a
`FaultPlan`'s ``replica_kill`` site on a fixed tick and kills live
replicas mid-decode (listener closed, in-flight client sockets
severed, serving loop stopped).  Greedy decoding is schedule- and
replica-independent, so an offline `engine.generate` run on an
identically-seeded engine is the byte-exact reference every streamed
answer — including ones resumed across a kill — must match.

In-process rather than subprocess on purpose: a killed replica must
look EXACTLY like a preempted VM from the network's point of view
(connection refused on new connects, reset on in-flight ones), which
`_TrackingHTTPServer.sever_all` delivers, while keeping the harness
fast enough for tier-1 (one tiny-model compile per replica, no
process spawn/jax re-import per respawn).

Used by `scripts/chaos_smoke.py --multi-replica N` and
`tests/test_serve_failover.py`.
"""
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence, Union

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu import logsys
from skypilot_tpu.infer.engine import InferenceEngine
from skypilot_tpu.infer.server import (InferenceServer,
                                       _BurstTolerantHTTPServer,
                                       _make_handler)
from skypilot_tpu.serve.load_balancer import SkyTpuLoadBalancer
from skypilot_tpu.serve.load_balancing_policies import LoadBalancingPolicy

logger = logsys.init_logger(__name__)


class _TrackingHTTPServer(_BurstTolerantHTTPServer):
    """ThreadingHTTPServer that can sever EVERY open connection.

    `shutdown()` only stops accepting; handler threads keep their
    sockets and finish politely — useless for simulating preemption.
    This server tracks accepted client sockets so `sever_all()` can
    close the listener AND reset the in-flight connections, which is
    what a killed VM looks like from the LB's side.
    """

    def __init__(self, *args, **kwargs):
        self._clients_lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.chaos._clients_lock')
        self._clients: set = set()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock, addr = super().get_request()
        with self._clients_lock:
            self._clients.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._clients_lock:
            self._clients.discard(request)
        super().shutdown_request(request)

    def sever_all(self) -> None:
        """Close the listener and hard-reset every open client socket."""
        try:
            self.socket.close()
        except OSError:
            pass
        with self._clients_lock:
            clients = list(self._clients)
            self._clients.clear()
        for sock in clients:
            # shutdown(), not close(): the handler thread's
            # rfile/wfile hold _io_refs on the socket, so close() from
            # here only decrements a refcount and the fd — and the
            # connection — would stay open until the handler exits.
            # shutdown tears the TCP stream down NOW regardless.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class KillableReplica:
    """One in-process replica that can be killed and respawned.

    The port is pinned at construction so the replica keeps its URL
    identity across kill/respawn — the LB's per-replica breaker state
    keys on the URL, and recovery (half-open probe succeeding against
    the respawned process) only makes sense at a stable address.
    """

    def __init__(self, make_engine: Callable[[], InferenceEngine],
                 port: int, host: str = '127.0.0.1',
                 tokenizer: Optional[object] = None):
        self.make_engine = make_engine
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.server: Optional[InferenceServer] = None
        self.httpd: Optional[_TrackingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.alive = False
        self.kills = 0

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def start(self, ready_timeout: float = 120.0) -> None:
        assert not self.alive, 'start() on a live replica'
        engine = self.make_engine()
        srv = InferenceServer(engine, tokenizer=self.tokenizer)
        srv.start()
        if not srv.ready.wait(ready_timeout):
            raise TimeoutError(
                f'replica :{self.port} never became ready')
        httpd = _TrackingHTTPServer((self.host, self.port),
                                    _make_handler(srv))
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  kwargs={'poll_interval': 0.05},
                                  daemon=True,
                                  name=f'replica-{self.port}')
        thread.start()
        self.server, self.httpd, self._thread = srv, httpd, thread
        self.alive = True

    def busy(self) -> bool:
        """True while a generate request is in flight (the interesting
        moment to kill)."""
        return self.alive and self.server is not None and \
            self.server.gen_inflight > 0

    def kill(self) -> None:
        """Preempt: RST every connection, stop accepting, stop the
        engine's serving loop.  From the LB's view this is a dead VM."""
        if not self.alive:
            return
        self.alive = False
        self.kills += 1
        httpd, srv = self.httpd, self.server
        self.httpd, self.server = None, None
        if httpd is not None:
            # Stop the accept loop BEFORE closing its socket: a closed
            # fd inside serve_forever's selector raises in that thread
            # and shutdown() would then wait on a loop that already
            # died.  Only after shutdown returns is the listener closed
            # (connects refuse) and every in-flight connection RST.
            httpd.shutdown()
            httpd.sever_all()
        if srv is not None:
            srv.stop()
        logger.info('chaos: killed replica :%d', self.port)

    def respawn(self, ready_timeout: float = 120.0) -> None:
        """Fresh engine + server on the SAME port (recovered VM)."""
        if self.alive:
            return
        self.start(ready_timeout)
        logger.info('chaos: respawned replica :%d', self.port)


def free_port(host: str = '127.0.0.1') -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ChaosFleet:
    """N killable replicas behind a standalone SkyTpuLoadBalancer.

    Standalone = `controller_url=None`: the replica set is seeded
    directly into the policy and stays FIXED across kills — ejection
    and re-admission of dead/respawned replicas is exactly the
    breaker/probe machinery under test, not set management.
    """

    def __init__(self,
                 make_engine: Union[Callable[[], InferenceEngine],
                                    Sequence[Callable[[],
                                                      InferenceEngine]]],
                 n_replicas: int, policy_name: str = 'least_load',
                 host: str = '127.0.0.1'):
        # One factory for a homogeneous fleet, or one PER replica for a
        # mixed one (e.g. a tp=2 replica next to single-chip ones — the
        # serve plane must treat both identically behind the LB).
        if callable(make_engine):
            factories = [make_engine] * n_replicas
        else:
            factories = list(make_engine)
            if len(factories) != n_replicas:
                raise ValueError(
                    f'{len(factories)} engine factories for '
                    f'{n_replicas} replicas')
        self.replicas = [
            KillableReplica(factory, free_port(host), host=host)
            for factory in factories
        ]
        self.policy = LoadBalancingPolicy.make(policy_name)
        self.policy.set_ready_replicas([r.url for r in self.replicas])
        self.lb = SkyTpuLoadBalancer(None, free_port(host), self.policy)
        self._lb_thread: Optional[threading.Thread] = None

    @property
    def lb_url(self) -> str:
        return f'http://127.0.0.1:{self.lb.port}'

    def start(self) -> None:
        for r in self.replicas:
            r.start()
        self._lb_thread = threading.Thread(target=self.lb.run,
                                           daemon=True, name='chaos-lb')
        self._lb_thread.start()
        deadline = time.monotonic() + 10  # det-ok: startup wait (harness)
        while time.monotonic() < deadline:  # det-ok: startup wait
            try:
                with socket.create_connection(
                        ('127.0.0.1', self.lb.port), timeout=0.2):
                    return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError('load balancer never came up')

    def live_replicas(self) -> List[KillableReplica]:
        return [r for r in self.replicas if r.alive]

    def kill_one(self, prefer_busy: bool = True) -> \
            Optional[KillableReplica]:
        """Kill one live replica — busy ones first (mid-decode kills
        are the case under test) — but NEVER the last live one: with
        zero replicas every request fails by construction and the run
        proves nothing about failover."""
        live = self.live_replicas()
        if len(live) <= 1:
            return None
        busy = [r for r in live if r.busy()] if prefer_busy else []
        victim = busy[0] if busy else live[0]
        victim.kill()
        return victim

    def respawn_dead(self) -> None:
        for r in self.replicas:
            if not r.alive:
                r.respawn()

    def stop(self) -> None:
        self.lb.stop()
        for r in self.replicas:
            r.kill()


class SeededKiller(threading.Thread):
    """Consults the plan's ``replica_kill`` site on a fixed tick and
    kills per its verdicts.  Determinism note: WHICH consult fires is a
    pure function of (seed, consult index); which replica dies and
    where its streams were depends on timing — the assertions
    (byte-identity of every completed answer) are timing-independent,
    which is the point.
    """

    def __init__(self, fleet: ChaosFleet, plan, tick_s: float = 0.05):
        super().__init__(daemon=True, name='chaos-killer')
        self.fleet = fleet
        self.plan = plan
        self.tick_s = tick_s
        self.kills = 0
        # NOT named _stop: that would shadow threading.Thread._stop,
        # which join() calls internally.
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            if self.plan.check('replica_kill') is not None:
                if self.fleet.kill_one() is not None:
                    self.kills += 1
            self._halt.wait(self.tick_s)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)
