"""Process-level chaos harness: a killable multi-replica fleet + LB.

The engine-level fault sites (`faults.py`) prove per-request
containment INSIDE one process.  This module proves the layer above —
the replica plane: N in-process replicas behind the real
`SkyTpuLoadBalancer`, with a seeded killer thread that consults a
`FaultPlan`'s ``replica_kill`` site on a fixed tick and kills live
replicas mid-decode (listener closed, in-flight client sockets
severed, serving loop stopped).  Greedy decoding is schedule- and
replica-independent, so an offline `engine.generate` run on an
identically-seeded engine is the byte-exact reference every streamed
answer — including ones resumed across a kill — must match.

In-process rather than subprocess on purpose: a killed replica must
look EXACTLY like a preempted VM from the network's point of view
(connection refused on new connects, reset on in-flight ones), which
`_TrackingHTTPServer.sever_all` delivers, while keeping the harness
fast enough for tier-1 (one tiny-model compile per replica, no
process spawn/jax re-import per respawn).

Used by `scripts/chaos_smoke.py --multi-replica N` and
`tests/test_serve_failover.py`.
"""
import json
import socket
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu import logsys
from skypilot_tpu.infer.engine import InferenceEngine
from skypilot_tpu.infer.server import (InferenceServer,
                                       _BurstTolerantHTTPServer,
                                       _make_handler)
from skypilot_tpu.serve.lb_journal import LBJournal
from skypilot_tpu.serve.load_balancer import SkyTpuLoadBalancer
from skypilot_tpu.serve.load_balancing_policies import LoadBalancingPolicy

logger = logsys.init_logger(__name__)


class _TrackingHTTPServer(_BurstTolerantHTTPServer):
    """ThreadingHTTPServer that can sever EVERY open connection.

    `shutdown()` only stops accepting; handler threads keep their
    sockets and finish politely — useless for simulating preemption.
    This server tracks accepted client sockets so `sever_all()` can
    close the listener AND reset the in-flight connections, which is
    what a killed VM looks like from the LB's side.
    """

    def __init__(self, *args, **kwargs):
        self._clients_lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.chaos._clients_lock')
        self._clients: set = set()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock, addr = super().get_request()
        with self._clients_lock:
            self._clients.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._clients_lock:
            self._clients.discard(request)
        super().shutdown_request(request)

    def sever_all(self) -> None:
        """Close the listener and hard-reset every open client socket."""
        try:
            self.socket.close()
        except OSError:
            pass
        with self._clients_lock:
            clients = list(self._clients)
            self._clients.clear()
        for sock in clients:
            # shutdown(), not close(): the handler thread's
            # rfile/wfile hold _io_refs on the socket, so close() from
            # here only decrements a refcount and the fd — and the
            # connection — would stay open until the handler exits.
            # shutdown tears the TCP stream down NOW regardless.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class KillableReplica:
    """One in-process replica that can be killed and respawned.

    The port is pinned at construction so the replica keeps its URL
    identity across kill/respawn — the LB's per-replica breaker state
    keys on the URL, and recovery (half-open probe succeeding against
    the respawned process) only makes sense at a stable address.
    """

    def __init__(self, make_engine: Callable[[], InferenceEngine],
                 port: int, host: str = '127.0.0.1',
                 tokenizer: Optional[object] = None):
        self.make_engine = make_engine
        self.host = host
        self.port = port
        self.tokenizer = tokenizer
        self.server: Optional[InferenceServer] = None
        self.httpd: Optional[_TrackingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.alive = False
        self.kills = 0

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def start(self, ready_timeout: float = 120.0) -> None:
        assert not self.alive, 'start() on a live replica'
        engine = self.make_engine()
        srv = InferenceServer(engine, tokenizer=self.tokenizer)
        srv.start()
        if not srv.ready.wait(ready_timeout):
            raise TimeoutError(
                f'replica :{self.port} never became ready')
        httpd = _TrackingHTTPServer((self.host, self.port),
                                    _make_handler(srv))
        httpd.daemon_threads = True
        thread = threading.Thread(target=httpd.serve_forever,
                                  kwargs={'poll_interval': 0.05},
                                  daemon=True,
                                  name=f'replica-{self.port}')
        thread.start()
        self.server, self.httpd, self._thread = srv, httpd, thread
        self.alive = True

    def busy(self) -> bool:
        """True while a generate request is in flight (the interesting
        moment to kill)."""
        return self.alive and self.server is not None and \
            self.server.gen_inflight > 0

    def kill(self) -> None:
        """Preempt: RST every connection, stop accepting, stop the
        engine's serving loop.  From the LB's view this is a dead VM."""
        if not self.alive:
            return
        self.alive = False
        self.kills += 1
        httpd, srv = self.httpd, self.server
        self.httpd, self.server = None, None
        if httpd is not None:
            # Stop the accept loop BEFORE closing its socket: a closed
            # fd inside serve_forever's selector raises in that thread
            # and shutdown() would then wait on a loop that already
            # died.  Only after shutdown returns is the listener closed
            # (connects refuse) and every in-flight connection RST.
            httpd.shutdown()
            httpd.sever_all()
        if srv is not None:
            srv.stop()
        logger.info('chaos: killed replica :%d', self.port)

    def respawn(self, ready_timeout: float = 120.0) -> None:
        """Fresh engine + server on the SAME port (recovered VM)."""
        if self.alive:
            return
        self.start(ready_timeout)
        logger.info('chaos: respawned replica :%d', self.port)


def free_port(host: str = '127.0.0.1') -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ChaosFleet:
    """N killable replicas behind a standalone SkyTpuLoadBalancer.

    Standalone = `controller_url=None`: the replica set is seeded
    directly into the policy and stays FIXED across kills — ejection
    and re-admission of dead/respawned replicas is exactly the
    breaker/probe machinery under test, not set management.
    """

    def __init__(self,
                 make_engine: Union[Callable[[], InferenceEngine],
                                    Sequence[Callable[[],
                                                      InferenceEngine]]],
                 n_replicas: int, policy_name: str = 'least_load',
                 host: str = '127.0.0.1',
                 journal_path: Optional[str] = None):
        # One factory for a homogeneous fleet, or one PER replica for a
        # mixed one (e.g. a tp=2 replica next to single-chip ones — the
        # serve plane must treat both identically behind the LB).
        if callable(make_engine):
            factories = [make_engine] * n_replicas
        else:
            factories = list(make_engine)
            if len(factories) != n_replicas:
                raise ValueError(
                    f'{len(factories)} engine factories for '
                    f'{n_replicas} replicas')
        self.replicas = [
            KillableReplica(factory, free_port(host), host=host)
            for factory in factories
        ]
        self.host = host
        self.policy_name = policy_name
        self.journal_path = journal_path
        # Degraded (gray-failure) proxies by replica index: routing
        # goes through the proxy URL while the replica itself stays
        # reachable at its own port (the two URLs are distinct replica
        # identities from the LB's point of view — deliberate, so the
        # probation verdict lands on the degraded path).
        self.degraded: Dict[int, 'DegradedReplica'] = {}
        # LB port pinned ONCE: kill_lb/restart_lb keep the URL clients
        # hold stable across LB generations (same contract the
        # supervisor gives the real serve plane).
        self.lb_port = free_port(host)
        self.lb_kills = 0
        self.lb_restarts = 0
        self.policy = LoadBalancingPolicy.make(policy_name)
        self.policy.set_ready_replicas(self._replica_urls())
        self.lb = SkyTpuLoadBalancer(
            None, self.lb_port, self.policy,
            journal=self._make_journal(),
            server_cls=_TrackingHTTPServer)
        self._lb_thread: Optional[threading.Thread] = None

    def _make_journal(self) -> Optional[LBJournal]:
        if not self.journal_path:
            return None
        return LBJournal(self.journal_path, clock=time.monotonic)

    def _replica_urls(self) -> List[str]:
        return [
            self.degraded[i].url if i in self.degraded else r.url
            for i, r in enumerate(self.replicas)
        ]

    @property
    def lb_url(self) -> str:
        return f'http://127.0.0.1:{self.lb.port}'

    def start(self) -> None:
        for r in self.replicas:
            r.start()
        self._lb_thread = threading.Thread(target=self.lb.run,
                                           daemon=True, name='chaos-lb')
        self._lb_thread.start()
        self._wait_lb_up()

    def _wait_lb_up(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout  # det-ok: startup wait (harness)
        while time.monotonic() < deadline:  # det-ok: startup wait
            try:
                with socket.create_connection(
                        ('127.0.0.1', self.lb.port), timeout=0.2):
                    return
            except OSError:
                time.sleep(0.05)
        raise TimeoutError('load balancer never came up')

    # ----------------------------------------------- control-plane chaos

    def kill_lb(self) -> None:
        """Crash the load balancer: listener closed, every in-flight
        proxied connection RST — from a client's view the service's one
        front door slams shut mid-stream."""
        lb, thread = self.lb, self._lb_thread
        # Crash fidelity: a SIGKILL'd LB never gets to journal its
        # in-flight lease releases.  Detach the journal BEFORE severing
        # so unwinding handler threads can't write `held: False` — the
        # successor must see the orphaned leases and adopt them.
        lb.journal = None
        httpd = lb._httpd  # pylint: disable=protected-access
        if httpd is not None:
            httpd.shutdown()
            httpd.sever_all()
        lb.stop()
        if thread is not None:
            thread.join(timeout=5)
        self.lb_kills += 1
        logger.info('chaos: killed LB :%d', self.lb_port)

    def restart_lb(self, wait_adopted: bool = True,
                   timeout: float = 10.0) -> None:
        """Bring a FRESH LB up on the same port (what the supervisor
        does in the real serve plane): new policy instance, journal
        re-adopted in the constructor.  With `wait_adopted`, block until
        the restarted LB has re-verified every journal-adopted replica
        with a live probe (adopted_unverified drains to []) — traffic
        sent before that may be quarantined away from healthy replicas.
        """
        policy = LoadBalancingPolicy.make(self.policy_name)
        policy.set_ready_replicas(self._replica_urls())
        self.policy = policy
        self.lb = SkyTpuLoadBalancer(
            None, self.lb_port, policy,
            journal=self._make_journal(),
            server_cls=_TrackingHTTPServer)
        self._lb_thread = threading.Thread(target=self.lb.run,
                                           daemon=True, name='chaos-lb')
        self._lb_thread.start()
        self._wait_lb_up(timeout)
        self.lb_restarts += 1
        if wait_adopted:
            deadline = time.monotonic() + timeout  # det-ok: harness wait
            while time.monotonic() < deadline:  # det-ok: harness wait
                try:
                    with urllib.request.urlopen(
                            f'{self.lb_url}/lb/stats', timeout=2) as resp:
                        stats = json.loads(resp.read())
                    if not stats.get('adopted_unverified'):
                        break
                except OSError:
                    pass
                time.sleep(0.05)
        logger.info('chaos: restarted LB :%d (journal=%s)', self.lb_port,
                    bool(self.journal_path))

    def lb_stats(self) -> Dict[str, Any]:
        """One `/lb/stats` snapshot from the CURRENT LB generation.
        The batch chaos leg reads this after restart_lb() to assert the
        journal hand-off (``batch_leases_adopted``) actually happened —
        a restart that silently dropped its leases would still pass the
        byte-identity check (the coordinator retries), so the counter
        is the only witness that recovery took the journal path."""
        with urllib.request.urlopen(f'{self.lb_url}/lb/stats',
                                    timeout=5) as resp:
            return json.loads(resp.read())

    def degrade_one(self, index: int, plan,
                    seed: int = 0) -> 'DegradedReplica':
        """Put a gray-failure proxy in front of replica `index` and
        re-seed routing through it.  The replica stays perfectly
        healthy; only its network path rots — the case the probation
        track exists for."""
        if index in self.degraded:
            return self.degraded[index]
        proxy = DegradedReplica(self.replicas[index], plan, seed=seed,
                                host=self.host)
        proxy.start()
        self.degraded[index] = proxy
        self.policy.set_ready_replicas(self._replica_urls())
        logger.info('chaos: degraded replica :%d behind proxy :%d',
                    self.replicas[index].port, proxy.port)
        return proxy

    def live_replicas(self) -> List[KillableReplica]:
        return [r for r in self.replicas if r.alive]

    def kill_one(self, prefer_busy: bool = True) -> \
            Optional[KillableReplica]:
        """Kill one live replica — busy ones first (mid-decode kills
        are the case under test) — but NEVER the last live one: with
        zero replicas every request fails by construction and the run
        proves nothing about failover."""
        live = self.live_replicas()
        if len(live) <= 1:
            return None
        busy = [r for r in live if r.busy()] if prefer_busy else []
        victim = busy[0] if busy else live[0]
        victim.kill()
        return victim

    def respawn_dead(self) -> None:
        for r in self.replicas:
            if not r.alive:
                r.respawn()

    def stop(self) -> None:
        self.lb.stop()
        for proxy in self.degraded.values():
            proxy.stop()
        for r in self.replicas:
            r.kill()


class SeededKiller(threading.Thread):
    """Consults the plan's ``replica_kill`` site on a fixed tick and
    kills per its verdicts.  Determinism note: WHICH consult fires is a
    pure function of (seed, consult index); which replica dies and
    where its streams were depends on timing — the assertions
    (byte-identity of every completed answer) are timing-independent,
    which is the point.
    """

    def __init__(self, fleet: ChaosFleet, plan, tick_s: float = 0.05):
        super().__init__(daemon=True, name='chaos-killer')
        self.fleet = fleet
        self.plan = plan
        self.tick_s = tick_s
        self.kills = 0
        self.lb_kills = 0
        # NOT named _stop: that would shadow threading.Thread._stop,
        # which join() calls internally.
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            if self.plan.check('replica_kill') is not None:
                if self.fleet.kill_one() is not None:
                    self.kills += 1
            if self.plan.check('lb_kill') is not None:
                # Kill + supervisor-style restart on the same port: the
                # window where clients see connection errors is the
                # restart latency, exactly as in the real serve plane.
                self.fleet.kill_lb()
                self.fleet.restart_lb()
                self.lb_kills += 1
            self._halt.wait(self.tick_s)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)


class DegradedReplica:
    """Gray-failure wrapper: a TCP splice proxy in front of a healthy
    replica.

    Crashes are the EASY failure — connection refused trips the
    breaker in seconds.  The failure that silently ruins a fleet's
    tail is the replica that stays alive and keeps answering probes
    while its responses crawl.  This proxy manufactures exactly that:
    the client→server direction passes through untouched, and each
    server→client chunk consults the plan's ``net_degrade`` site — a
    firing spec either sleeps ``delay_s ± jitter_s`` (seeded uniform)
    before relaying, or, with ``blackhole``, stops relaying the
    connection's downstream bytes entirely (a hung-but-open socket).

    The proxy has its own pinned port: the LB routes to the PROXY url,
    so from the control plane's view the degraded path IS the replica —
    TTFT samples, probation verdicts, and weight shed all land on it
    while the wrapped engine stays pristine.
    """

    def __init__(self, inner: KillableReplica, plan, seed: int = 0,
                 host: str = '127.0.0.1'):
        self.inner = inner
        self.plan = plan
        self.host = host
        self.port = free_port(host)
        # Jitter draws come from the proxy's own seeded stream (NOT the
        # plan's per-spec streams, which must stay consult-aligned).
        self._rng = np.random.default_rng(seed)
        self._rng_lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.chaos.degraded._rng_lock')
        self._halt = threading.Event()
        self._listener: Optional[socket.socket] = None
        self.chunks_delayed = 0
        self.chunks_blackholed = 0

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def start(self) -> None:
        listener = socket.socket()
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self.host, self.port))
        listener.listen(64)
        self._listener = listener
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f'degrade-{self.port}').start()

    def stop(self) -> None:
        self._halt.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._halt.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(
                    (self.inner.host, self.inner.port), timeout=5)
            except OSError:
                client.close()
                continue
            threading.Thread(
                target=self._splice, args=(client, upstream, False),
                daemon=True, name=f'degrade-up-{self.port}').start()
            threading.Thread(
                target=self._splice, args=(upstream, client, True),
                daemon=True, name=f'degrade-down-{self.port}').start()

    def _splice(self, src: socket.socket, dst: socket.socket,
                degrade: bool) -> None:
        """Relay src→dst until either side dies.  Only the downstream
        (server→client) direction is degraded: requests arrive intact,
        responses rot — the asymmetry real congested paths show."""
        blackholed = False
        try:
            while not self._halt.is_set():
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                if degrade and not blackholed:
                    spec = self.plan.check('net_degrade')
                    if spec is not None:
                        if spec.blackhole:
                            # Hung-but-open: swallow this and every
                            # later downstream byte; the client waits
                            # on a socket that never speaks again.
                            blackholed = True
                            self.chunks_blackholed += 1
                        elif spec.delay_s > 0.0:
                            with self._rng_lock:
                                jitter = float(self._rng.uniform(
                                    -spec.jitter_s, spec.jitter_s))
                            time.sleep(max(0.0, spec.delay_s + jitter))
                            self.chunks_delayed += 1
                if blackholed and degrade:
                    continue
                try:
                    dst.sendall(chunk)
                except OSError:
                    break
        finally:
            for sock in (src, dst):
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass
