"""Continuous-batching inference engine.

Architecture (TPU-first, JetStream-shaped):

- **KV cache, two layouts**:
  * **Dense slotted** (default): one [num_slots, Hkv, max_cache_len, D]
    pair per layer, allocated once.  A request occupies a slot from
    prefill until EOS/max-tokens, then the slot is recycled — decode
    batch shape never changes, so the decode step compiles exactly once.
    Every slot reserves max_cache_len rows up front and every decode
    step streams the full cache width.
  * **Block-paged** (kv_block_size > 0): one [kv_blocks, Hkv,
    kv_block_size, D] pool per layer plus a host-side block allocator
    with per-slot block tables.  Decode gathers only a slot's allocated
    blocks (ceil(len/block) blocks, padded to a small set of
    block-count buckets so compiles stay O(#buckets)) — HBM read
    traffic is proportional to tokens actually held, and slot capacity
    becomes a shared pool instead of num_slots * max_cache_len rows.
    kv_block_size must divide max_cache_len, every prefill bucket, and
    prefill_chunk.  Block 0 is a reserved dump block absorbing idle-
    lane and overrun writes.  Admission rule: a request is started only
    when free blocks cover its worst-case demand,
    ceil(min(prompt + max_new - 1, max_cache_len) / block), beyond what
    already-running slots may still allocate — otherwise it waits
    (serving: deferred FIFO; offline: left pending), so a running slot
    can never hit pool exhaustion mid-flight.  Registered prefixes
    live in pool blocks and are SHARED copy-free: a prefix hit appends
    refcounted block ids to the slot's table instead of copying rows
    (only a partial tail block is privatized by one block copy).
- **Bucketed prefill**: prompts are right-padded to a small set of bucket
  lengths, so there are O(#buckets) prefill compilations.  Prefill runs
  the full forward through the same cached-attention path and its KV rows
  are inserted into the slot with one dynamic_update_slice per layer.
- **Jitted windowed decode**: ONE device dispatch runs `decode_steps`
  scanned decode steps for ALL slots (lax.scan) and returns a [K, B]
  token block — host dispatch + device-to-host sync amortize over K
  tokens.  Cache buffers are donated so XLA updates them in place;
  sampling (greedy / temperature) happens on-device.  A slot reaching
  EOS/max_new mid-window generated up to K-1 speculative tokens: the
  host discards them, and their cache rows are dead until the slot is
  recycled (prefill insert overwrites).
- **Continuous batching**: the scheduler fills free slots from the pending
  queue between decode steps — no stop-the-world batching.

Role parity: replaces the reference's delegation to vLLM/JetStream
(llm/vllm/, examples/tpu/v6e/serve-llama2-7b.yaml); the serve plane's
replicas run this engine via `python -m skypilot_tpu.infer.server`.
"""
import collections
import contextlib
import dataclasses
import queue
import threading
import time
from typing import (Any, Callable, Dict, List, Optional,
                    Sequence, Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.infer import block_pool as block_pool_mod
from skypilot_tpu.infer import qos as qos_mod
from skypilot_tpu.infer import scheduler as scheduler_mod
from skypilot_tpu.infer.radix import RadixTree
from skypilot_tpu.models.llama import (Llama, LlamaConfig, init_cache,
                                       init_paged_cache)

DEFAULT_PREFILL_BUCKETS = (64, 128, 256, 512, 1024, 2048)

_CACHE_DTYPES = {
    'bfloat16': jnp.bfloat16,
    'bf16': jnp.bfloat16,
    'fp8': jnp.float8_e4m3fn,
    'float8_e4m3fn': jnp.float8_e4m3fn,
    'float32': jnp.float32,
}


def resolve_cache_dtype(name: str):
    """CLI string -> KV-cache dtype.  fp8 (e4m3) halves cache HBM per
    slot — measured ~+9% decode throughput at equal slot count on v5e —
    at a small quantization cost (no per-tensor scales: K/V magnitudes
    sit comfortably inside e4m3's +-448 range for trained models)."""
    try:
        return _CACHE_DTYPES[name]
    except KeyError:
        raise ValueError(
            f'unknown cache dtype {name!r}; one of '
            f'{sorted(_CACHE_DTYPES)}') from None


@dataclasses.dataclass
class InferConfig:
    model: str = 'llama-1b'
    num_slots: int = 8
    max_cache_len: int = 2048
    prefill_buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS
    max_new_tokens: int = 128
    eos_id: Optional[int] = None
    cache_dtype: Any = jnp.bfloat16
    # Decode steps per device dispatch (lax.scan window).  >1 amortizes
    # host dispatch + device-to-host sync over K tokens — the dominant
    # cost of token-by-token loops.  A slot finishing mid-window wastes at
    # most K-1 speculative tokens (discarded on the host), so keep K small
    # enough that overrun stays cheap; 8 measured ~8x over K=1 on v5e.
    decode_steps: int = 8
    # Serving only (generate_stream): max prefills between decode windows,
    # so in-flight requests keep generating while a burst of new requests
    # prefills instead of stalling behind the whole burst.
    prefills_per_gap: int = 4
    # Queue-aware adaptive decode window (latency serving): full
    # decode_steps windows while nothing is waiting (per-dispatch fixed
    # cost amortizes over the whole window — TPOT = s + F/K), SHORT
    # (2-step) windows only while an arrival is queued with a free slot
    # to take it (it then waits at most 2 steps for a prefill gap).
    # One extra compile (the short window's scan length).  See
    # _select_window; policy history in docs/performance.md (the r4
    # occupancy-based variant shortened windows for lone streams and
    # lost on high-RTT chips).
    adaptive_decode_window: bool = False
    # Decode lookahead (serving only): dispatch window N+1 from the
    # device-resident final tokens of window N before paying window N's
    # host transfer — steady-state decode pays max(RTT, compute) per
    # window instead of RTT + compute.  Slot finishes during the
    # in-flight window are tolerated (their lookahead columns are
    # discarded; cache writes are the dead rows windowed decode already
    # tolerates); prefills consume the pending window first.  Gated off
    # while arrivals wait (the in-flight window would add TTFT) and
    # under speculative decoding.  See _maybe_dispatch_ahead.
    decode_lookahead: bool = False
    # Prompts prefilled per device dispatch (fixed batched-prefill width;
    # short chunks pad by duplicating a real lane).  Amortizes
    # per-dispatch latency the same way decode_steps does for decode.
    prefill_lanes: int = 4
    # Chunked prefill: 0 = monolithic (today's behavior).  > 0 splits a
    # long prompt into prefill_chunk-sized pieces forwarded one per
    # serving gap over the slot's already-written KV rows, so active
    # slots stall for ONE chunk instead of the whole prefill: worst-case
    # time-between-tokens drops from full_prefill_ms to chunk_ms +
    # window_ms (docs/performance.md).  Also lifts the largest-bucket
    # prompt cap: prompts beyond the largest configured bucket are
    # accepted (up to max_cache_len - max_new) and always chunked, so
    # the auto-appended max_cache_len bucket — and its compile — go
    # away.  Must divide max_cache_len (chunk writes start at multiples
    # of the chunk and must never clamp at the cache end).  Requests
    # wanting prompt_logprobs bypass chunking (non-final chunk logits
    # are discarded).  Serving only for in-bucket prompts: offline
    # generate() chunks only prompts no bucket can hold.
    prefill_chunk: int = 0
    # Speculative decoding via prompt-lookup (n-gram) drafting: 0
    # disables (windowed decode).  With draft_len=D, every decode
    # dispatch feeds [last_token, d1..dD] — D draft tokens proposed by
    # matching the slot's recent n-gram against its own prompt+output
    # history — through ONE [B, 1+D] cached forward and accepts the
    # longest draft prefix the model agrees with (greedy slots only;
    # sampled slots fall back to 1 token/dispatch).  Decode is
    # weight-streaming-bound, so a [B, 1+D] forward costs about the same
    # HBM traffic as [B, 1]: accepted drafts are (nearly) free tokens.
    # Wins on input-grounded output (summarization, code edit, RAG);
    # on unrelated output acceptance ~0 and windowed decode is faster.
    # Parity: vLLM's prompt-lookup speculator (the reference delegates
    # serving to vLLM); JetStream has no speculative path.
    draft_len: int = 0
    # Longest n-gram tried (then n-1 ... 1) when drafting.
    ngram_max: int = 4
    # Multi-LoRA serving (the reference's LoRAX recipe, llm/lorax/,
    # rebuilt natively): lora_rank > 0 builds the model with
    # lora_max_adapters STACKED zero-init adapters; register_adapter
    # loads trained adapter weights (train/lora.py save_adapter_npz
    # artifacts) into a stack slot, and each Request may name an
    # adapter — concurrent requests for different adapters (and the
    # base model) decode in ONE batch via per-slot adapter ids.
    lora_rank: int = 0
    lora_max_adapters: int = 8
    lora_alpha: float = 16.0
    # Alternatives reported per position in RequestResult.top_logprobs
    # (OpenAI `logprobs`/`top_logprobs` k; the API caps requests at 5).
    # STATIC at trace time — one jax.lax.top_k over the log-softmax the
    # sampling path already computes, so the cost is one [B, V] top-k
    # and a [B, K] transfer per step.  Entry 0 is always the argmax
    # (is_greedy for eval harnesses).
    logprob_topk: int = 5
    # Block-paged KV cache: 0 = dense slotted layout (one
    # [num_slots, Hkv, max_cache_len, D] pair per layer).  > 0 pages
    # the cache into kv_block_size-row blocks drawn from a shared pool:
    # decode streams ceil(len/block)*block rows per step instead of
    # max_cache_len, and prefix reuse shares blocks copy-free.  Must
    # divide max_cache_len, every prefill bucket, and prefill_chunk
    # (so no block-spanning write ever straddles an unallocated
    # boundary).  Llama-family models only.
    kv_block_size: int = 0
    # Pool size in blocks (including the reserved dump block 0).  None
    # = full provisioning: num_slots * (max_cache_len / block) + 1, so
    # admission never defers — size it smaller to oversubscribe slots
    # against typical (shorter-than-max) request lengths, or larger to
    # leave headroom for registered prefixes (their blocks are pool-
    # resident too).  See the admission rule in the module docstring.
    kv_blocks: Optional[int] = None
    # Automatic prefix caching (requires kv_block_size > 0): an
    # engine-level radix tree keyed on kv_block_size-token runs indexes
    # the pool blocks of completed (and chunk-boundary) prompts, and
    # every admitted prompt reuses its longest block-aligned cached
    # prefix copy-free (refcount bump + suffix-only prefill).  Nothing
    # to register: the tree builds itself from traffic and sheds
    # unreferenced leaves LRU-first under pool pressure, BEFORE
    # admission control defers a request.  register_prefix becomes
    # optional pinning (pinned nodes are eviction-exempt).  Greedy
    # token streams are byte-identical with this on or off: only
    # prefill-written full PROMPT blocks are ever indexed, and the
    # suffix attends over the same quantized rows a full prefill would
    # have written.  Parity: vLLM automatic-prefix-caching / SGLang
    # RadixAttention at block granularity.
    auto_prefix_cache: bool = False
    # Host-RAM KV tier (requires auto_prefix_cache): byte budget for
    # the second tier of the paged pool.  When radix eviction would
    # free a recently-referenced node's block, its rows are copied to
    # host RAM asynchronously first (LRU within the budget); the next
    # radix match restores them with jax.device_put overlapped with
    # the suffix-only prefill, so the restore latency hides behind
    # compute the request needs anyway.  The host form is topology-
    # neutral (global [L, Hkv, block, D] rows), so a block spilled
    # from a tp=2 replica restores onto tp=1 or tp=4.  0 disables.
    # Greedy streams stay byte-identical with the tier on or off:
    # restored rows are exact copies of the spilled cache-dtype rows.
    host_kv_bytes: int = 0
    # Prefix KV caching: registered prefixes (system prompts) keep
    # their per-layer KV rows resident on device; a request whose
    # prompt starts with a registered prefix prefills ONLY its suffix —
    # TTFT drops by the prefix share of prefill compute.  Rows are
    # stored in cache_dtype, so reuse is bit-identical to a one-shot
    # prefill (the suffix attends over the same quantized rows either
    # way).  Max prefixes resident (LRU evicted); 0 disables.
    # Parity: vLLM automatic-prefix-caching, here with explicit
    # registration (engine.register_prefix / POST /cache_prefix).
    max_prefixes: int = 16
    # Stall bound for benchmark_serving/run(): if NO request completes
    # for this many seconds while results are outstanding, the run is
    # declared stalled and aborted with the engine's stats() in the
    # error (replaces the old hard-coded 3600 s wait, under which a
    # dead serving loop stranded every client for an hour).  Progress
    # resets the window, so long runs are bounded by per-completion
    # gaps, not total wall time.
    run_stall_timeout_s: float = 120.0
    # QoS serving (infer/qos.py): replace FIFO admission with priority
    # classes (interactive > batch) + per-tenant weighted-fair
    # queueing, let interactive arrivals preempt part-prefilled batch
    # prompts at chunked-prefill boundaries (paged + radix only:
    # parked blocks stay refcounted in the tree, resume is a
    # suffix-only prefill), and shed queued work whose projected
    # (queue + prefill + decode) time cannot meet its deadline_s —
    # typed rejection at dequeue, not a timeout.  Offline generate()
    # is unaffected (no queue, no scheduler).
    qos: bool = False
    # Per-tenant WFQ weights: Request.tenant_id -> relative share
    # (default 1.0 for unlisted tenants).  Read only when qos=True.
    qos_tenant_weights: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Request:
    tokens: List[int]
    max_new_tokens: Optional[int] = None
    temperature: float = 0.0
    request_id: Optional[str] = None
    # When the request entered the SYSTEM (HTTP handler / bench feeder).
    # TTFT measures from here, so time spent queued for a free slot
    # counts — otherwise load would silently vanish from the metric.
    arrival_time: Optional[float] = None
    # Streaming: called (from the engine thread, under the engine lock)
    # with each batch of newly generated token ids for THIS request —
    # keep it cheap (a queue put).  The final RequestResult still
    # arrives through the normal path after the last chunk.
    stream_cb: Optional[Callable[[List[int]], None]] = None
    # Multi-LoRA: name of a registered adapter (None = base model).
    adapter: Optional[str] = None
    # Score the PROMPT too: RequestResult.prompt_logprobs carries
    # log P(token_t | tokens_<t) for t >= 1 (None at t=0) — the
    # lm-eval-harness loglikelihood pattern (OpenAI echo+logprobs).
    # Such requests bypass prefix-KV reuse (reused rows have no
    # logits).
    want_prompt_logprobs: bool = False
    # Per-request deadline, in seconds from submit/arrival (serving:
    # arrival_time when set, else the engine's dequeue time).  Enforced
    # ENGINE-side: an expired request is evicted mid-decode — slot and
    # paged blocks freed, finish_reason='deadline', partial output
    # returned — so a client that stopped caring never holds a lane.
    deadline_s: Optional[float] = None
    # QoS class: 'interactive' (the default when None) or 'batch' —
    # see infer/qos.PRIORITY_CLASSES.  Unknown values are rejected as
    # client errors.  Ordering only matters when InferConfig.qos is
    # on; batch prompts may additionally be preempted mid-prefill and
    # resumed later (the stream is unaffected: nothing has been
    # emitted before the first token).
    priority: Optional[str] = None
    # Fair-queueing key: requests sharing a tenant_id share one WFQ
    # lane (weighted by InferConfig.qos_tenant_weights); None rides
    # the shared default lane.  Also the LB's rate-limit key.
    tenant_id: Optional[str] = None


@dataclasses.dataclass
class RequestResult:
    request_id: Optional[str]
    prompt_tokens: List[int]
    output_tokens: List[int]
    ttft_s: float                 # arrival/submit -> first token
    latency_s: float              # arrival/submit -> last token
    finish_reason: str            # 'eos' | 'length' | 'error' | 'deadline'
    error: Optional[str] = None
    error_class: Optional[str] = None   # 'client' | 'internal' | 'shed'
    # log P(token | context) for each generated token (always present
    # on success — computed on-device next to sampling, cost is one
    # logsumexp the softmax path needs anyway).
    logprobs: Optional[List[float]] = None
    # The top-k alternatives at each generated position: a list of
    # (token_id, logprob) pairs, best first — entry 0 is the argmax
    # (equals the chosen token for greedy requests; is_greedy for eval
    # harnesses).  k = InferConfig.logprob_topk (OpenAI top_logprobs).
    top_logprobs: Optional[List[List[Tuple[int, float]]]] = None
    # Prompt scores (want_prompt_logprobs): entry t is
    # log P(prompt_t | prompt_<t); entry 0 is None (no context).
    prompt_logprobs: Optional[List[Optional[float]]] = None
    # Top-k alternatives per prompt position (aligned with
    # prompt_logprobs; entry 0 is None).
    prompt_top_logprobs: Optional[List[Optional[List[Tuple[
        int, float]]]]] = None


def prompt_lookup_draft(hist: Sequence[int], k: int,
                        ngram_max: int) -> List[int]:
    """Prompt-lookup drafting: propose up to `k` tokens continuing the
    most recent earlier occurrence of the history's trailing n-gram
    (longest n first).  Pure host-side numpy over the slot's own
    prompt+output tokens — no draft model, no device work."""
    length = len(hist)
    if length < 2 or k < 1:
        return []
    h = np.asarray(hist, np.int32)
    from numpy.lib.stride_tricks import sliding_window_view
    for n in range(min(ngram_max, length - 1), 0, -1):
        tail = h[length - n:]
        # Window starts 0..length-1-n: the trailing n-gram itself
        # (start length-n) is excluded, so a match is a genuine earlier
        # occurrence.
        windows = sliding_window_view(h[:length - 1], n)
        cand = np.flatnonzero((windows == tail).all(axis=1))
        if cand.size:
            start = int(cand[-1]) + n
            proposal = h[start:start + k]
            if proposal.size:
                return proposal.tolist()
    return []


def _pairs(ids_row, lps_row) -> List[Tuple[int, float]]:
    """[k] ids + [k] logprobs -> [(id, lp), ...] best-first (the
    host-side shape of one position's top_logprobs entry)."""
    return [(int(i), float(l)) for i, l in zip(ids_row, lps_row)]


def _unpack_head(buf: np.ndarray, topk: int):
    """Host inverse of the jitted pack_head: one transferred f32 block
    [..., 2+2k] -> (tokens i32, logprobs f32, top-k ids i32, top-k lps
    f32).  The id columns were bitcast on device; a same-itemsize view
    restores them losslessly (the transfer is a byte copy)."""
    toks = np.ascontiguousarray(buf[..., 0]).view(np.int32)
    lps = buf[..., 1]
    tids = np.ascontiguousarray(buf[..., 2:2 + topk]).view(np.int32)
    tlps = buf[..., 2 + topk:]
    return toks, lps, tids, tlps


class _Slot:
    __slots__ = ('request', 'length', 'generated', 'submit_time',
                 'first_token_time', 'max_new', 'streamed', 'lps',
                 'tops', 'prompt_lps', 'prompt_tops')

    def __init__(self, request: Request, length: int, submit_time: float,
                 max_new: int):
        self.request = request
        self.length = length               # filled cache positions
        self.generated: List[int] = []
        self.submit_time = submit_time
        self.first_token_time: Optional[float] = None
        self.max_new = max_new
        self.streamed = 0                  # tokens already stream_cb'd
        self.lps: List[float] = []         # logprob per generated token
        # Per generated token: top-k (id, logprob) pairs, argmax first.
        self.tops: List[List[Tuple[int, float]]] = []
        self.prompt_lps: Optional[list] = None
        self.prompt_tops: Optional[list] = None


class _ChunkJob:
    """A prompt mid-chunked-prefill.  Owns its slot (excluded from
    _free_slot) but has no _Slot yet: rows [0, done) of the slot hold
    its prompt KV; the host length mirror tracks `done` so interleaved
    decode's dead-row writes for this lane land at the frontier, past
    the already-written prompt rows.  The slot activates (becomes a
    _Slot, first token sampled) on the final chunk."""
    __slots__ = ('req', 'slot', 'submit_time', 'n', 'max_new', 'done',
                 'aid')

    def __init__(self, req: Request, slot: int, submit_time: float,
                 n: int, max_new: int, aid: int):
        self.req = req
        self.slot = slot
        self.submit_time = submit_time
        self.n = n                         # total prompt tokens
        self.max_new = max_new
        self.done = 0                      # prompt rows already written
        self.aid = aid


# Backends whose int32<->f32 bitcast pack/unpack path has been verified
# bit-exact this process, keyed by (backend, topk).  See
# _check_bitcast_roundtrip.
_BITCAST_CHECKED: set = set()


def _check_bitcast_roundtrip(topk: int) -> None:
    """Startup self-check for the packed-transfer path (ADVICE r5): the
    engine bitcasts int32 token ids into an f32 block on device
    (pack_head) and restores them host-side via a same-itemsize numpy
    view (_unpack_head).  That is bit-exact on XLA CPU/TPU/GPU today,
    but any backend or transfer layer that canonicalizes NaNs, flushes
    subnormals, or converts instead of byte-copying would silently
    corrupt token ids everywhere.  Round-trip id patterns that alias
    the dangerous f32 encodings (quiet/signaling NaN, infinity,
    subnormals, -0.0) through a jitted pack once per (backend, topk)
    and fail loudly on mismatch."""
    key = (jax.default_backend(), topk)
    if key in _BITCAST_CHECKED:
        return
    ids = np.array([0, 1, -1,
                    2**31 - 1,             # largest NaN bit pattern
                    -2**31,                # -0.0
                    0x7fc00000,            # f32 quiet NaN bit pattern
                    0x7f800001,            # signaling NaN
                    0xffc00000 - 2**32,    # -NaN (sign-bit set)
                    0x7f800000,            # +inf
                    0x00400000,            # subnormal
                    101, 31999], np.int32)
    b = ids.size
    # Bit-pattern-diverse top-k ids without int32 overflow: XOR shifts.
    tids = ids[:, None] ^ np.arange(topk, dtype=np.int32)[None]
    f32 = jnp.float32

    def pack(chosen, lp, top_ids, top_lps):
        # Mirrors pack_head exactly (same concat layout, same bitcasts).
        return jnp.concatenate([
            jax.lax.bitcast_convert_type(chosen, f32)[..., None],
            lp[..., None].astype(f32),
            jax.lax.bitcast_convert_type(top_ids, f32),
            top_lps.astype(f32)], axis=-1)

    buf = np.asarray(jax.jit(pack)(
        jnp.asarray(ids), jnp.linspace(-2.0, 0.0, b, dtype=jnp.float32),
        jnp.asarray(tids), jnp.zeros((b, topk), jnp.float32)))
    toks, _, rtids, _ = _unpack_head(buf, topk)
    if not (np.array_equal(toks, ids) and np.array_equal(rtids, tids)):
        raise RuntimeError(
            f'int32<->f32 bitcast pack/unpack round-trip is not '
            f'bit-exact on backend {jax.default_backend()!r}: token ids '
            'would be silently corrupted in every dispatch (NaN '
            'canonicalization / subnormal flush / non-byte-copy '
            'transfer).  Serve on a backend with exact bitcast '
            'transfers.')
    _BITCAST_CHECKED.add(key)


class InferenceEngine:
    """Single-process engine over the local device(s).

    mesh: a Mesh with a 'tensor' axis enables tensor-parallel serving —
    params shard by their logical axes (heads/mlp/vocab over 'tensor'),
    the KV cache shards on its kv-heads dim, and XLA inserts the
    activation collectives over ICI; num_kv_heads must be divisible by
    the tensor degree.  mesh=None: everything resident on one chip.
    """

    def __init__(self, model_config: LlamaConfig,
                 cfg: Optional[InferConfig] = None,
                 params: Optional[Any] = None,
                 rng: Optional[jax.Array] = None,
                 mesh: Optional[jax.sharding.Mesh] = None):
        from skypilot_tpu.models.gpt2 import GPT2Config
        from skypilot_tpu.models.mixtral import MixtralConfig
        self._mesh = mesh
        self.model_config = model_config
        self.cfg = cfg or InferConfig()
        if not isinstance(model_config,
                          (LlamaConfig, MixtralConfig, GPT2Config)):
            raise TypeError(
                'InferenceEngine supports the Llama, Mixtral and GPT-2 '
                'families (KV-cache decode path); got '
                f'{type(model_config).__name__}')
        # Tensor degree of this replica (1 when unsharded): divides the
        # per-chip KV byte accounting in stats()/kv_health() and rides
        # /healthz so the serve plane can tell TP replicas from DP ones
        # in a mixed fleet.
        self._tp = 1
        if mesh is not None:
            tp = dict(mesh.shape).get('tensor', 1)
            if model_config.num_kv_heads % max(tp, 1):
                raise ValueError(
                    f'num_kv_heads {model_config.num_kv_heads} not '
                    f'divisible by tensor degree {tp}')
            self._tp = max(tp, 1)
        if self.cfg.max_cache_len > model_config.max_seq_len:
            raise ValueError(
                f'max_cache_len {self.cfg.max_cache_len} exceeds model '
                f'max_seq_len {model_config.max_seq_len}')
        if self.cfg.decode_steps < 1:
            # 0 would scan zero steps, append zero tokens, and spin the
            # generate loop forever.
            raise ValueError(
                f'decode_steps must be >= 1 (got {self.cfg.decode_steps})')
        if self.cfg.prefills_per_gap < 1:
            # 0 would block every new prefill while ANY slot is active,
            # collapsing serving concurrency to one request at a time.
            raise ValueError(f'prefills_per_gap must be >= 1 '
                             f'(got {self.cfg.prefills_per_gap})')
        if self.cfg.prefill_lanes < 1:
            raise ValueError(f'prefill_lanes must be >= 1 '
                             f'(got {self.cfg.prefill_lanes})')
        if self.cfg.prefill_chunk < 0:
            raise ValueError(f'prefill_chunk must be >= 0 '
                             f'(got {self.cfg.prefill_chunk})')
        if self.cfg.prefill_chunk and \
                self.cfg.max_cache_len % self.cfg.prefill_chunk:
            # Chunk writes are C-wide dynamic_update_slices starting at
            # multiples of C: divisibility guarantees start + C <=
            # max_cache_len, so the write is NEVER clamped — a clamped
            # start (> M - C) would silently rewrite the slot's own
            # earlier, still-live prompt rows with wrong-position K/V.
            raise ValueError(
                f'max_cache_len ({self.cfg.max_cache_len}) must be a '
                f'multiple of prefill_chunk ({self.cfg.prefill_chunk})')
        self._paged = self.cfg.kv_block_size > 0
        if self.cfg.kv_block_size < 0:
            raise ValueError(f'kv_block_size must be >= 0 '
                             f'(got {self.cfg.kv_block_size})')
        if self._paged:
            bs_ = self.cfg.kv_block_size
            if not isinstance(model_config, LlamaConfig):
                raise TypeError(
                    'the block-paged KV cache supports the llama '
                    f'family; got {type(model_config).__name__}')
            if self.cfg.max_cache_len % bs_:
                raise ValueError(
                    f'max_cache_len ({self.cfg.max_cache_len}) must be '
                    f'a multiple of kv_block_size ({bs_})')
            if self.cfg.prefill_chunk and self.cfg.prefill_chunk % bs_:
                raise ValueError(
                    f'prefill_chunk ({self.cfg.prefill_chunk}) must be '
                    f'a multiple of kv_block_size ({bs_})')
        if self.cfg.auto_prefix_cache and not self._paged:
            raise ValueError(
                'auto_prefix_cache requires the block-paged KV cache '
                '(set kv_block_size > 0): the radix tree shares pool '
                'blocks by refcount')
        if self.cfg.draft_len < 0:
            raise ValueError(f'draft_len must be >= 0 '
                             f'(got {self.cfg.draft_len})')
        if self.cfg.draft_len + 1 >= self.cfg.max_cache_len:
            raise ValueError(
                f'draft_len + 1 ({self.cfg.draft_len + 1}) must be < '
                f'max_cache_len ({self.cfg.max_cache_len})')
        if self.cfg.draft_len and self.cfg.ngram_max < 1:
            raise ValueError(f'ngram_max must be >= 1 '
                             f'(got {self.cfg.ngram_max})')
        if not 1 <= self.cfg.logprob_topk <= model_config.vocab_size:
            raise ValueError(
                f'logprob_topk must be in [1, vocab_size='
                f'{model_config.vocab_size}] (got '
                f'{self.cfg.logprob_topk})')
        if self.cfg.run_stall_timeout_s <= 0:
            raise ValueError(f'run_stall_timeout_s must be > 0 '
                             f'(got {self.cfg.run_stall_timeout_s})')
        # Failure/recovery observability (stats()['faults'], /stats):
        #   internal_errors      requests failed with error_class='internal'
        #   deadline_evictions   requests evicted past Request.deadline_s
        #   loop_restarts        serving-loop supervisor restarts
        #   quarantined_batches  unattributed decode failures that failed
        #                        the whole active batch (+ cache rebuild)
        #   nonfinite_lanes      lanes killed by the non-finite logit guard
        self.fault_stats = {'internal_errors': 0, 'deadline_evictions': 0,  # guarded-by: _lock
                            'loop_restarts': 0, 'quarantined_batches': 0,
                            'nonfinite_lanes': 0}
        # Deterministic fault injection (tests/chaos only): an armed
        # faults.FaultPlan consulted at named sites via _fault()/
        # _fault_raise().  None = unarmed = one attribute check per site.
        self._faults = None
        # Requests failed from INSIDE the dispatch path (non-finite
        # guard) — drained by _harvest into the normal delivery path.
        self._pending_failures: List[Tuple[Request, RequestResult]] = []  # guarded-by: _lock
        # Speculation observability: dispatches that ran the verify path,
        # draft tokens offered, draft tokens accepted (acceptance rate =
        # accepted/offered; extra tok/dispatch = accepted/dispatches).
        self.spec_stats = {'dispatches': 0, 'drafted': 0, 'accepted': 0}  # guarded-by: _lock
        # Adaptive dispatch policy: a verify yields 1+accepted tokens
        # per slot for ONE weight-stream, the windowed decode
        # decode_steps tokens for decode_steps streams — so speculation
        # pays only when enough drafts are likely right.  Track an
        # acceptance EMA (optimistic start so grounded traffic engages
        # immediately); when the expected bonus falls below half a
        # token per active slot, run windowed and only re-probe
        # occasionally (ungrounded traffic must not pay a coincidental
        # draft's 1-token dispatch for the whole batch).
        self._accept_ema = 0.5  # guarded-by: _lock
        self._spec_skips = 0  # guarded-by: _lock
        # Prefix KV cache: token-tuple -> per-layer [(k, v)] rows
        # ([Hkv, L, D], cache dtype, device-resident), LRU-ordered
        # (OrderedDict[Tuple[int, ...], list]).
        self._prefixes = collections.OrderedDict()  # guarded-by: _lock
        # Requests whose prefill reused a cached prefix / prefix tokens
        # skipped (prefill compute saved, in tokens).
        self.prefix_stats = {'hits': 0, 'tokens_reused': 0}  # guarded-by: _lock
        # Multi-LoRA serving: rebuild the config with stacked zero-init
        # adapters (zero-delta init == base model until registered).
        self._adapter_names: Dict[str, int] = {}
        if self.cfg.lora_rank:
            if not isinstance(model_config, LlamaConfig):
                raise TypeError(
                    'multi-LoRA serving supports the llama family; got '
                    f'{type(model_config).__name__}')
            if self.cfg.lora_max_adapters < 1:
                raise ValueError('lora_max_adapters must be >= 1')
            model_config = dataclasses.replace(
                model_config, lora_rank=self.cfg.lora_rank,
                lora_alpha=self.cfg.lora_alpha,
                lora_num_adapters=self.cfg.lora_max_adapters)
            self.model_config = model_config
        # Mixtral rides the same engine: shared attention geometry means
        # llama.init_cache covers its KV cache, and the MoE block's
        # router + experts simply run on the new tokens inside the same
        # jitted prefill/decode (expert weights shard over 'tensor' by
        # their 'expert' logical axis = expert-parallel TP serving).
        # Parity: the reference delegates Mixtral serving to vLLM
        # (llm/mixtral/serve.yaml:38).
        from skypilot_tpu.models import registry as model_registry
        self.model = model_registry.build_model(model_config)
        # init must thread adapter_ids when the model has stacked
        # adapters (they require the argument even at trace time).
        if self.cfg.lora_rank:
            self._init_fn = lambda r, s: self.model.init(
                r, s, adapter_ids=jnp.zeros((s.shape[0],), jnp.int32))
        else:
            self._init_fn = self.model.init
        buckets = tuple(b for b in self.cfg.prefill_buckets
                        if b <= self.cfg.max_cache_len)
        if not buckets or (buckets[-1] < self.cfg.max_cache_len
                           and not self.cfg.prefill_chunk):
            # Cover the (largest-bucket, cache-len] gap so any prompt the
            # cache can hold has a bucket.  With chunked prefill the gap
            # is served by chunking instead — the max_cache_len bucket
            # (and its compile) is dropped from the set.
            buckets += (self.cfg.max_cache_len,)
        self.cfg.prefill_buckets = buckets
        if self._paged:
            bs_ = self.cfg.kv_block_size
            bad = [b for b in buckets if b % bs_]
            if bad:
                raise ValueError(
                    f'every prefill bucket must be a multiple of '
                    f'kv_block_size ({bs_}); got {bad}')
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._rng = rng
        sample = jnp.zeros((1, 8), jnp.int32)
        if params is not None and self.cfg.lora_rank:
            # A given (base) tree lacks the adapter leaves: init the
            # full adapter-bearing tree, then graft the base weights in
            # (unboxed: merge_base_params reads leaf dtype/sharding).
            import flax.linen as nn
            from skypilot_tpu.train.lora import merge_base_params
            full = (jax.jit(self._init_fn)(rng, sample) if mesh is None
                    else self._init_sharded_params(rng, sample))
            full = nn.meta.unbox(full)
            params = {'params': merge_base_params(
                full['params'], nn.meta.unbox(params)['params'])}
        elif params is None:
            if mesh is None:
                params = jax.jit(self._init_fn)(rng, sample)
            else:
                params = self._init_sharded_params(rng, sample)
        elif mesh is not None:
            params = self._shard_given_params(params, rng, sample)
        else:
            # A given (possibly host/numpy) tree must live on device
            # ONCE: leaving numpy leaves would silently re-upload the
            # whole model on EVERY dispatch (hundreds of MB per decode
            # window through a tunneled chip).
            params = jax.tree.map(jnp.asarray, params)
        self.params = params
        b = self.cfg.num_slots
        self._pool: Optional[block_pool_mod.BlockPool] = None
        if self._paged:
            bs_ = self.cfg.kv_block_size
            max_blocks = self.cfg.max_cache_len // bs_
            n_blocks = self.cfg.kv_blocks
            if n_blocks is None:
                # Full provisioning (+1 dump block): admission never
                # defers, so dense and paged engines schedule
                # identically — the capacity win comes from RAISING
                # num_slots over a fixed pool instead.
                n_blocks = b * max_blocks + 1
            if n_blocks < max_blocks + 1:
                raise ValueError(
                    f'kv_blocks ({n_blocks}) must be >= max_cache_len/'
                    f'kv_block_size + 1 ({max_blocks + 1}): one '
                    'full-length request must fit the pool')
            # Host-side allocator (infer/block_pool.py): refcounts,
            # free list, per-slot block tables, pool geometry.  The
            # engine exposes the historical _block_refs/_tables_np/...
            # attribute names as read-only properties onto the pool so
            # the sanitizers and tests keep one accounting view.
            self._pool = block_pool_mod.BlockPool(n_blocks, bs_,
                                                  max_blocks, b)
            self.paged_stats = {'deferred': 0, 'prefix_block_hits': 0}  # guarded-by: _lock
        # Automatic radix-tree prefix caching over the pool (None when
        # off).  Must exist before _reset_cache(), which drops the tree
        # on every (re)build.  radix_stats always exists so stats()
        # reports one shape regardless of layout/knobs.
        self._radix = (RadixTree(self.cfg.kv_block_size)
                       if self._paged and self.cfg.auto_prefix_cache
                       else None)
        self.radix_stats = {'hits': 0, 'tokens_reused': 0, 'lookups': 0,  # guarded-by: _lock
                            'inserts': 0, 'evictions': 0}
        if self.cfg.host_kv_bytes < 0:
            raise ValueError(f'host_kv_bytes must be >= 0 '
                             f'(got {self.cfg.host_kv_bytes})')
        # Host-RAM KV tier: second tier of the pool, fed by radix
        # eviction (so it requires the radix tree).  Survives
        # _reset_cache() — host copies are keyed by token content, not
        # pool state, so they stay valid across a quarantine rebuild.
        self._host_tier = (block_pool_mod.HostKVTier(
            self.cfg.host_kv_bytes, self.cfg.kv_block_size,
            recency_window=max(64, 4 * self._pool._num_blocks))
                           if self._radix is not None
                           and self.cfg.host_kv_bytes > 0 else None)
        # Drain-time hot-set handoff counters (export_hot_prefixes /
        # adopt_prefixes), reported under kv.host_tier.
        self.handoff_stats = {'exported': 0, 'adopted': 0}  # guarded-by: _lock
        self._reset_cache()
        # Requests dequeued but not admissible yet (paged admission
        # control); always present so the serving loop can poll it
        # without caring about the layout.
        self._deferred: List[Request] = []  # guarded-by: _lock
        # Admission-order seam (infer/scheduler.py): the serving loop
        # drains its client queue into this scheduler and admits in
        # whatever order it yields — strict FIFO by default, priority
        # classes + per-tenant WFQ when cfg.qos is on (infer/qos.py).
        # The scheduler carries its own lock (stats() reads cross-
        # thread); it never calls back into the engine.
        self._sched: scheduler_mod.Scheduler = (
            qos_mod.WfqScheduler(
                weights=self.cfg.qos_tenant_weights,
                cost_fn=lambda r: len(r.tokens) + self._max_new(r))
            if self.cfg.qos else scheduler_mod.FifoScheduler())
        # QoS observability (stats()['qos'], /stats):
        #   preemptions  batch chunk jobs parked for interactive work
        #   sheds        typed deadline rejections at dequeue
        self.qos_stats = {'preemptions': 0, 'sheds': 0}  # guarded-by: _lock
        # Per-tenant admitted/shed counters (bounded: overflow tenants
        # beyond _MAX_TENANT_ROWS fold into one row).
        self._tenant_qos: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock
        # Observed service rate (tokens/s per request, EWMA) feeding
        # the deadline-projection shed bound; fed in _finish_slot
        # under _lock.
        self._svc_estimator = qos_mod.ServiceEstimator()
        self._slots: List[Optional[_Slot]] = [None] * b  # guarded-by: _lock
        # Request ids cancelled while still PENDING (not yet slotted):
        # generate_stream drops them at dequeue/prefill time.  In-slot
        # cancels free the slot directly (cancel()).  id -> mark time:
        # marks expire (_CANCEL_MARK_TTL_S) so a cancel that raced a
        # natural finish cannot leak forever or poison a later request
        # reusing the same client-supplied id.
        self._cancelled: Dict[str, float] = {}  # guarded-by: _lock
        # Arrivals snapshot for the window policy (_select_window):
        # generate_stream records the request-queue depth just before
        # each step; 0 outside the serving loop, so offline generate()
        # always runs full windows.
        self._arrivals_hint = 0  # guarded-by: _lock
        # Decode lookahead state: a dispatched-but-unconsumed window
        # (packed handle, device-side token/length chain, slot
        # snapshot, prefill epoch), plus the serving-loop flag that
        # gates lookahead (offline generate() never speculates).  The
        # epoch bumps on every prefill so an in-flight window's chain
        # is never extended across a slot recycle.  See
        # _maybe_dispatch_ahead.
        self._ahead = None  # guarded-by: _lock
        self._serving = False
        self._prefill_epoch = 0  # guarded-by: _lock
        # Chunked prefill state: slot -> _ChunkJob for prompts whose KV
        # rows are being written one prefill_chunk per serving gap
        # (_chunk_round).  A chunking slot is reserved (not free) but
        # has no _Slot yet.
        self._chunking: Dict[int, _ChunkJob] = {}  # guarded-by: _lock
        self.chunk_stats = {'rounds': 0, 'chunks': 0, 'requests': 0}  # guarded-by: _lock
        # Phantom-arrival decay (ADVICE r5): consecutive serve-loop
        # dequeue passes that yielded ONLY cancelled requests.  The
        # queue depth then mostly counts tombstones, so the arrivals
        # hint — which forces short windows and disables lookahead — is
        # right-shifted by the streak (_serve_loop) instead of taking
        # qsize() at face value.
        self._cancel_only_streak = 0
        # Host mirrors of per-slot decode state (pushed to device each
        # step as small arrays).
        self._lengths = np.zeros((b,), np.int32)  # guarded-by: _lock
        self._last_tokens = np.zeros((b,), np.int32)  # guarded-by: _lock
        self._temps = np.zeros((b,), np.float32)  # guarded-by: _lock
        self._slot_adapters = np.full((b,), -1, np.int32)  # guarded-by: _lock
        self._lock = sanitizers.instrument_lock(threading.Lock(),
                                                'infer.engine._lock')
        self._jit_fns()   # lazy wrappers; tracing happens (under _ctx)
                          # at the _start_batch/_decode_step call sites
        # Every dispatch's token ids ride the bitcast-packed transfer:
        # verify it is bit-exact on this backend before serving anything.
        _check_bitcast_roundtrip(self.cfg.logprob_topk)

    def _reset_cache(self):  # locked: _lock
        """(Re)create the device KV cache and, when paged, reset the
        host-side allocator to empty.  Used at construction and by the
        quarantine path after an UNATTRIBUTED dispatch failure: a jitted
        call that fails after buffer donation leaves self.cache pointing
        at deleted buffers, so without a rebuild every later dispatch —
        including fresh prefills — would fail too and the engine would
        be bricked per-process instead of degraded per-request.

        Caller must hold no live slots (the quarantine path fails them
        all first).  Paged prefixes live in the pool, so a paged rebuild
        drops them (re-registration re-prefills); dense prefixes are
        separate buffers and survive.
        """
        if self._paged:
            self.cache = init_paged_cache(self.model_config,
                                          self._num_blocks,
                                          self.cfg.kv_block_size,
                                          self.cfg.cache_dtype)
            self._pool.reset()
            self._prefixes.clear()
            if self._radix is not None:
                # The tree's block references die with the pool; the
                # generation bump invalidates any match taken against
                # the pre-reset tree (it rebuilds from traffic).
                self._radix.clear()
        else:
            self.cache = init_cache(self.model_config, self.cfg.num_slots,
                                    self.cfg.max_cache_len,
                                    self.cfg.cache_dtype)
        if self._mesh is not None:
            # Cache [B, Hkv, S, D] (paged: [N, Hkv, bs, D]): kv heads
            # shard like the weights' 'kv_heads' logical axis (the
            # per-shard K/V the sharded projections produce) — resolved
            # through the same rules as every other sharding, not a
            # hand-named mesh axis.  Both layouts carry kv-heads on
            # dim 1, so one sharding covers them.
            from skypilot_tpu.parallel import mesh as mesh_lib
            cache_sharding = mesh_lib.named_sharding(
                self._mesh, None, 'kv_heads', None, None)
            self.cache = [
                (jax.device_put(k, cache_sharding),
                 jax.device_put(v, cache_sharding)) for k, v in self.cache
            ]

    # ------------------------------------------------------ fault plans

    def arm_faults(self, plan):
        """Arm a faults.FaultPlan: the engine consults it at named sites
        (see faults.SITES).  Tests/chaos tooling only."""
        self._faults = plan

    def disarm_faults(self):
        self._faults = None

    def _fault(self, site: str):
        """One consult of a named injection site.  The unarmed path is
        a single attribute check — zero overhead in production."""
        if self._faults is None:
            return None
        return self._faults.check(site)

    def _fault_raise(self, site: str):
        """Consult and raise InjectedFault if the plan fires.  Called
        HOST-SIDE before dispatches: a post-donation device failure
        would invalidate the cache, which is the unattributed-
        quarantine case, not the per-slot one (faults.py docstring)."""
        sp = self._fault(site)
        if sp is not None:
            from skypilot_tpu.infer.faults import InjectedFault
            raise InjectedFault(
                f'{sp.message} [site={site}]', site,
                slots=None if sp.slot is None else [sp.slot])

    # ---------------------------------------------------------- sharding

    def _ctx(self):
        """Mesh + flax logical-axis-rules context (trace-time logical
        constraints inside the model need the rules active); a
        nullcontext when unsharded."""
        if self._mesh is None:
            return contextlib.nullcontext()
        from skypilot_tpu.parallel import mesh as mesh_lib
        return mesh_lib.mesh_context(self._mesh)

    def _param_shardings(self, rng, sample):
        import flax.linen as nn

        from skypilot_tpu.parallel import mesh as mesh_lib
        abstract = jax.eval_shape(self._init_fn, rng, sample)
        logical = nn.get_partition_spec(abstract)
        shardings = jax.tree.map(
            lambda spec: nn.logical_to_mesh_sharding(
                spec, self._mesh, mesh_lib.logical_axis_rules()),
            logical,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        # Replicate any dim the mesh doesn't divide evenly (e.g. an odd
        # vocab under tensor parallelism) instead of failing placement.
        return jax.tree.map(
            lambda leaf, sh: self._fit_sharding(leaf.shape, sh),
            nn.meta.unbox(abstract), nn.meta.unbox(shardings))

    def _fit_sharding(self, shape, sharding):
        mesh_shape = dict(self._mesh.shape)

        def degree(ax):
            axes = ax if isinstance(ax, tuple) else (ax,)
            d = 1
            for a in axes:
                d *= mesh_shape.get(a, 1)
            return d

        spec = tuple(sharding.spec) + (None,) * (len(shape) -
                                                 len(sharding.spec))
        fitted = tuple(
            ax if ax is not None and dim % degree(ax) == 0 else None
            for dim, ax in zip(shape, spec))
        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(*fitted))

    def _init_sharded_params(self, rng, sample):
        """Params born sharded over the mesh (a 70B never materializes
        on one device)."""
        import flax.linen as nn
        shardings = self._param_shardings(rng, sample)

        def init_unboxed(r):
            # Unbox INSIDE jit so the output pytree structure matches
            # the (unboxed) shardings tree.
            return nn.meta.unbox(self._init_fn(r, sample))

        with self._ctx():
            return jax.jit(init_unboxed, out_shardings=shardings)(rng)

    def _shard_given_params(self, params, rng, sample):
        """Place a given (host or single-device) param tree onto the
        mesh by its logical axes — the HF-import serving path."""
        import flax.linen as nn
        params = nn.meta.unbox(params)   # strip partitioning boxes
        shardings = self._param_shardings(rng, sample)
        return jax.tree.map(
            lambda p, s: jax.device_put(np.asarray(p), s), params,
            shardings)

    # ------------------------------------------------------------- jitted

    def _jit_fns(self) -> None:
        model = self.model
        use_lora = self.cfg.lora_rank > 0

        def akw(adapter_ids):
            """Thread per-row adapter ids into the model only when the
            model actually carries stacked adapters (other families'
            __call__ doesn't take the argument)."""
            return {'adapter_ids': adapter_ids} if use_lora else {}

        def chosen_logprob(logits, chosen):
            """log softmax of `chosen` ([...]) under `logits` ([..., V])
            — one logsumexp, cheap next to the forward."""
            logz = jax.scipy.special.logsumexp(logits, axis=-1)
            sel = jnp.take_along_axis(logits, chosen[..., None],
                                      axis=-1)[..., 0]
            return sel - logz

        topk = self.cfg.logprob_topk

        def topk_lp(logits):
            """([..., k] token ids, [..., k] logprobs), best first: the
            OpenAI top_logprobs alternatives (entry 0 = argmax, so
            is_greedy for evals is free).  One top-k over the same
            log-softmax the sampling path computes."""
            logz = jax.scipy.special.logsumexp(logits, axis=-1)
            vals, ids = jax.lax.top_k(logits, topk)
            return ids.astype(jnp.int32), vals - logz[..., None]

        def pack_head(chosen, chosen_lp, top_ids, top_lps):
            """Bitcast-pack sampled tokens + logprobs + top-k
            alternatives into ONE f32 block [..., 2 + 2*topk].  The
            host then reads a single device->host transfer per
            dispatch instead of four: on a tunneled chip every
            transfer is a full round trip (~100 ms measured,
            scripts/bench_decode_micro.py) and the extra three
            dominated TPOT.  Unpacked by _unpack_head."""
            f32 = jnp.float32
            return jnp.concatenate([
                jax.lax.bitcast_convert_type(chosen, f32)[..., None],
                chosen_lp[..., None].astype(f32),
                jax.lax.bitcast_convert_type(top_ids, f32),
                top_lps.astype(f32)], axis=-1)

        def prefill_insert(params, tokens, true_lens, pcache, cache,
                           slots, temps, rng, adapter_ids, want_plp):
            """Fused batched prefill: P prompts forward + first-token
            sampling + KV insertion into their slots, ONE dispatch.

            tokens [P, bucket]; true_lens/slots/temps [P]; pcache: fresh
            [P, Hkv, bucket, D] pairs; cache: the engine cache (donated).
            Compiles once per (bucket, P).
            """
            p = tokens.shape[0]
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None], tokens.shape)
            logits, pc = model.apply(params, tokens, positions, pcache,
                                     **akw(adapter_ids))
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            greedy = jnp.argmax(last, axis=-1)
            sampled = jax.random.categorical(
                rng, last / jnp.maximum(temps, 1e-4)[:, None], axis=-1)
            first = jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
            first_lp = chosen_logprob(last, first)
            first_top = topk_lp(last)                    # [P, k] x2
            if want_plp:   # STATIC: prompt scoring is a full [P,S,V]
                # reduction pass + transfer — only when a request in
                # the chunk asked (position t-1 predicts token t).
                prompt_lps = chosen_logprob(logits[:, :-1],
                                            tokens[:, 1:])  # [P, S-1]
                prompt_tops = topk_lp(logits[:, :-1])    # [P, S-1, k]
                # [P, S-1, 1+2k]: lp + bitcast ids + lps, one block.
                prompt_packed = jnp.concatenate([
                    prompt_lps[..., None],
                    jax.lax.bitcast_convert_type(prompt_tops[0],
                                                 jnp.float32),
                    prompt_tops[1].astype(jnp.float32)], axis=-1)
            else:
                p_ = tokens.shape[0]
                prompt_packed = jnp.zeros((p_, 0, 1 + 2 * topk),
                                          jnp.float32)

            new_cache = []
            for (k, v), (pk, pv) in zip(cache, pc):

                def write(i, kv, pk=pk, pv=pv):
                    kk, vv = kv
                    sk = jax.lax.dynamic_slice_in_dim(pk, i, 1, 0)
                    sv = jax.lax.dynamic_slice_in_dim(pv, i, 1, 0)
                    at = (slots[i], 0, 0, 0)
                    return (jax.lax.dynamic_update_slice(
                                kk, sk.astype(kk.dtype), at),
                            jax.lax.dynamic_update_slice(
                                vv, sv.astype(vv.dtype), at))

                kk, vv = jax.lax.fori_loop(0, p, write, (k, v))
                new_cache.append((kk, vv))
            return (pack_head(first, first_lp, *first_top),
                    prompt_packed, new_cache)

        def decode(params, cache, tokens, lengths, temps, rng,
                   adapter_ids, steps):
            # tokens/lengths/temps: [B]; `steps` (STATIC) tokens for
            # every slot in ONE dispatch (lax.scan), returning [K, B]
            # tokens.  steps = decode_steps normally; 2 when the
            # queue-aware adaptive window engages (_select_window).
            def one_step(carry, key):
                cache, tokens, lengths = carry
                positions = lengths[:, None]
                logits, cache = model.apply(params, tokens[:, None],
                                            positions, cache,
                                            **akw(adapter_ids))
                logits = logits[:, 0]                        # [B, V]
                greedy = jnp.argmax(logits, axis=-1)
                temps_safe = jnp.maximum(temps, 1e-4)[:, None]
                sampled = jax.random.categorical(key, logits / temps_safe,
                                                 axis=-1)
                next_tokens = jnp.where(temps > 0, sampled,
                                        greedy).astype(jnp.int32)
                lp = chosen_logprob(logits, next_tokens)
                t_ids, t_lps = topk_lp(logits)               # [B, k]
                return (cache, next_tokens, lengths + 1), (
                    next_tokens, lp, t_ids, t_lps)

            keys = jax.random.split(rng, steps)
            (cache, last, lens), (toks, lps, gtoks, glps) = jax.lax.scan(
                one_step, (cache, tokens, lengths), keys)
            # One packed [K, B, 2+2*topk] block: single host transfer.
            # last/lens stay DEVICE-resident: decode lookahead feeds
            # them straight into the next dispatch so it never waits on
            # this window's host round trip (_maybe_dispatch_ahead).
            return pack_head(toks, lps, gtoks, glps), last, lens, cache

        def spec_verify(params, cache, tokens, lengths, temps, rng,
                        adapter_ids):
            """One speculative verify dispatch.  tokens [B, 1+D]: column
            0 is each slot's last generated token, columns 1.. are
            drafts.  All 1+D rows are written to the cache (rows past
            the accepted prefix are dead — the next dispatch's writes
            start at the accepted length and cover them before any
            query position reaches them, the same invariant as windowed
            decode's EOS overrun).  Returns preds [B, 1+D]: the model's
            next token after each fed position."""
            k = tokens.shape[1]
            positions = lengths[:, None] + jnp.arange(k)[None]
            logits, cache = model.apply(params, tokens, positions, cache,
                                        **akw(adapter_ids))
            greedy = jnp.argmax(logits, axis=-1)             # [B, K]
            temps_safe = jnp.maximum(temps, 1e-4)[:, None, None]
            sampled = jax.random.categorical(rng, logits / temps_safe,
                                             axis=-1)
            preds = jnp.where(temps[:, None] > 0, sampled,
                              greedy).astype(jnp.int32)
            preds_lp = chosen_logprob(logits, preds)         # [B, K]
            t_ids, t_lps = topk_lp(logits)                   # [B, K, k]
            return pack_head(preds, preds_lp, t_ids, t_lps), cache

        cache_dtype = self.cfg.cache_dtype

        def prefill_capture(params, tokens, pcache, adapter_ids):
            """Forward a prefix [1, bucket] and return its KV rows (the
            register_prefix path; logits are discarded)."""
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None], tokens.shape)
            _, pc = model.apply(params, tokens, positions, pcache,
                                **akw(adapter_ids))
            return pc

        def prefix_prefill(params, tokens, start, true_lens, prefix_kv,
                           rem_kv, cache, slots, temps, rng,
                           adapter_ids):
            """Lane-batched suffix prefill over shared preloaded prefix
            KV: P matched prompts forward only their suffixes, sample
            first tokens, and insert all start+SB rows per slot — one
            dispatch (the prefix-reuse twin of prefill_insert).

            tokens [P, SB] (suffixes); start is a DYNAMIC traced
            scalar, so the compile key is SHAPES only — (B, SB) where
            B = pow2_floor(start) — and distinct registered-prefix
            lengths share executables (O(#buckets * #suffix_buckets)
            compiles, not one per length).  prefix_kv: per-layer
            ([Hkv, B, D]) pairs = prefix rows [0, B); rem_kv: same
            shape, rows [0, start-B) holding prefix rows [B, start)
            (rest zero).  The lane cache is the concat [B | B | SB]:
            row index == position for every row a query can see —
            remainder rows sit at indices B..start-1, and the zero rows
            at [start, 2B) are all overwritten by the suffix's own
            writes (positions start..start+SB-1) before attention, so
            padding is never read.  Since start < 2B, the final rows
            [0, start+SB) are written back to the slot in two
            static-width updates: [0, B) and a (B+SB)-wide window at
            dynamic offset start-B (the overlap [start-B, B) rewrites
            identical prefix rows).
            """
            p, sb = tokens.shape
            positions = start + jnp.broadcast_to(
                jnp.arange(sb)[None], tokens.shape)
            b_ = prefix_kv[0][0].shape[1]
            pcache = []
            for (pk, pv), (rk, rv) in zip(prefix_kv, rem_kv):
                hkv, _, hd = pk.shape
                pad = jnp.zeros((p, hkv, sb, hd), cache_dtype)

                def bcast(x, p=p):
                    return jnp.broadcast_to(
                        x[None].astype(cache_dtype), (p,) + x.shape)

                pcache.append(
                    (jnp.concatenate([bcast(pk), bcast(rk), pad],
                                     axis=2),
                     jnp.concatenate([bcast(pv), bcast(rv), pad],
                                     axis=2)))
            logits, pc = model.apply(params, tokens, positions, pcache,
                                     **akw(adapter_ids))
            last = jnp.take_along_axis(
                logits, (true_lens - 1)[:, None, None], axis=1)[:, 0]
            greedy = jnp.argmax(last, axis=-1)
            sampled = jax.random.categorical(
                rng, last / jnp.maximum(temps, 1e-4)[:, None], axis=-1)
            first = jnp.where(temps > 0, sampled,
                              greedy).astype(jnp.int32)
            first_lp = chosen_logprob(last, first)
            first_top = topk_lp(last)                    # [P, k] x2
            new_cache = []
            for (k, v), (pk2, pv2) in zip(cache, pc):
                hkv, _, hd = pk2.shape[1:]

                def write(i, kv, pk2=pk2, pv2=pv2, hkv=hkv, hd=hd):
                    kk, vv = kv

                    def upd(dst, lane, ofs, width):
                        sl = jax.lax.dynamic_slice(
                            lane, (i, 0, ofs, 0), (1, hkv, width, hd))
                        return jax.lax.dynamic_update_slice(
                            dst, sl.astype(dst.dtype),
                            (slots[i], 0, ofs, 0))

                    kk = upd(kk, pk2, 0, b_)
                    vv = upd(vv, pv2, 0, b_)
                    kk = upd(kk, pk2, start - b_, b_ + sb)
                    vv = upd(vv, pv2, start - b_, b_ + sb)
                    return kk, vv

                kk, vv = jax.lax.fori_loop(0, p, write, (k, v))
                new_cache.append((kk, vv))
            return pack_head(first, first_lp, *first_top), new_cache

        def chunk_prefill(params, tokens, starts, true_pos, cache,
                          temps, rng, adapter_ids):
            """One chunked-prefill dispatch, full slot width, directly
            over the live engine cache (the generalization of
            prefix_prefill's "suffix over preloaded rows" with DYNAMIC
            per-lane starts — one compile total instead of one per
            offset).  tokens [B, C]: lane i's next C prompt tokens
            (zero-padded past the prompt); starts [B]: each lane's
            write offset — a chunking lane's frontier (its rows
            [0, start) already hold this prompt's KV from earlier
            chunks), an active lane's length (dead-row writes past its
            live rows, the invariant windowed decode already relies
            on), 0 for idle lanes.  true_pos [B]: index WITHIN the
            chunk of the last real token — only final chunks read the
            sampled head.  The caller guarantees start + C <=
            max_cache_len for every lane (config divisibility + the
            _chunk_round clamp guard), so no write is ever clamped
            onto live rows.  One dispatch advances EVERY in-progress
            chunk job."""
            c = tokens.shape[1]
            positions = starts[:, None] + jnp.arange(c)[None]
            logits, cache = model.apply(params, tokens, positions, cache,
                                        **akw(adapter_ids))
            last = jnp.take_along_axis(
                logits, true_pos[:, None, None], axis=1)[:, 0]  # [B, V]
            greedy = jnp.argmax(last, axis=-1)
            sampled = jax.random.categorical(
                rng, last / jnp.maximum(temps, 1e-4)[:, None], axis=-1)
            first = jnp.where(temps > 0, sampled,
                              greedy).astype(jnp.int32)
            first_lp = chosen_logprob(last, first)
            first_top = topk_lp(last)                    # [B, k] x2
            return pack_head(first, first_lp, *first_top), cache

        bs = self.cfg.kv_block_size

        def pkw(tables):
            """Thread the block tables + block size into the model's
            paged attention path (llama family only)."""
            return {'paged_tables': tables, 'paged_block_size': bs}

        # Head-sharded pool pinning: under a mesh every paged root
        # constrains the pool to the registry layout P(None, 'kv_heads',
        # None, None) on entry AND exit, so XLA keeps block gathers and
        # scatter-writes chip-local to the owned heads and the donated
        # buffers never pay a relayout between dispatches.  Block ids
        # stay global (the host allocator, tables and radix tree are
        # topology-oblivious) — only the pages are distributed.
        if self._mesh is not None and self._paged:
            from skypilot_tpu.parallel import mesh as mesh_lib
            pool_sharding = self._fit_sharding(
                self.cache[0][0].shape,
                mesh_lib.named_sharding(self._mesh, None, 'kv_heads',
                                        None, None))

            def pin_pool(cache):
                return [
                    (jax.lax.with_sharding_constraint(k, pool_sharding),
                     jax.lax.with_sharding_constraint(v, pool_sharding))
                    for k, v in cache]

            # Host-tier restore rows [L, G, Hkv, bs, D]: kv-heads on
            # dim 2 shard like the pool's dim 1, so a device_put of the
            # topology-neutral host form lands each chip's head shard
            # directly (no all-gather on the restore path).  The G dim
            # varies per call; only the FIXED hkv dim must divide, so
            # one representative shape fits them all.
            self._rows_sharding = self._fit_sharding(
                (len(self.cache), 1) + self.cache[0][0].shape[1:],
                mesh_lib.named_sharding(self._mesh, None, None,
                                        'kv_heads', None, None))
        else:
            def pin_pool(cache):
                return cache

            self._rows_sharding = None

        def paged_prefill(params, tokens, starts, true_pos, cache,
                          tables, temps, rng, adapter_ids, want_plp):
            """The ONE paged prefill dispatch: forwards tokens [P, W] at
            positions starts + arange(W) directly over the block pool
            via per-lane tables [P, NB], samples at true_pos (index
            WITHIN the window of the last real token), and returns the
            packed head.  Serves monolithic bucket prefill (starts=0),
            copy-free suffix prefill over shared prefix blocks (starts=
            prefix len — DYNAMIC, so no per-start recompile like the
            dense prefix_prefill), chunk rounds (full slot width), and
            prefix capture (1 lane, head discarded).  Writes past a
            lane's allocated blocks land in the dump block; rows there
            are beyond every query position, so the attention mask
            never sees them."""
            cache = pin_pool(cache)
            w = tokens.shape[1]
            positions = starts[:, None] + jnp.arange(w)[None]
            logits, cache = model.apply(params, tokens, positions, cache,
                                        **pkw(tables),
                                        **akw(adapter_ids))
            last = jnp.take_along_axis(
                logits, true_pos[:, None, None], axis=1)[:, 0]
            greedy = jnp.argmax(last, axis=-1)
            sampled = jax.random.categorical(
                rng, last / jnp.maximum(temps, 1e-4)[:, None], axis=-1)
            first = jnp.where(temps > 0, sampled,
                              greedy).astype(jnp.int32)
            first_lp = chosen_logprob(last, first)
            first_top = topk_lp(last)                    # [P, k] x2
            if want_plp:   # STATIC (monolithic starts=0 lanes only)
                prompt_lps = chosen_logprob(logits[:, :-1],
                                            tokens[:, 1:])  # [P, W-1]
                prompt_tops = topk_lp(logits[:, :-1])
                prompt_packed = jnp.concatenate([
                    prompt_lps[..., None],
                    jax.lax.bitcast_convert_type(prompt_tops[0],
                                                 jnp.float32),
                    prompt_tops[1].astype(jnp.float32)], axis=-1)
            else:
                prompt_packed = jnp.zeros((tokens.shape[0], 0,
                                           1 + 2 * topk), jnp.float32)
            return (pack_head(first, first_lp, *first_top),
                    prompt_packed, pin_pool(cache))

        def paged_decode(params, cache, tokens, lengths, temps, rng,
                         adapter_ids, tables, steps):
            """Windowed decode over the block pool: identical scan to
            `decode`, with writes/gathers routed through the per-slot
            tables.  The host pre-allocates blocks covering every write
            position of the window, so the tables are constant through
            the scan."""
            def one_step(carry, key):
                cache, tokens, lengths = carry
                positions = lengths[:, None]
                logits, cache = model.apply(params, tokens[:, None],
                                            positions, cache,
                                            **pkw(tables),
                                            **akw(adapter_ids))
                logits = logits[:, 0]
                greedy = jnp.argmax(logits, axis=-1)
                temps_safe = jnp.maximum(temps, 1e-4)[:, None]
                sampled = jax.random.categorical(
                    key, logits / temps_safe, axis=-1)
                next_tokens = jnp.where(temps > 0, sampled,
                                        greedy).astype(jnp.int32)
                lp = chosen_logprob(logits, next_tokens)
                t_ids, t_lps = topk_lp(logits)
                return (cache, next_tokens, lengths + 1), (
                    next_tokens, lp, t_ids, t_lps)

            keys = jax.random.split(rng, steps)
            (cache, last, lens), (toks, lps, gtoks, glps) = jax.lax.scan(
                one_step, (pin_pool(cache), tokens, lengths), keys)
            return (pack_head(toks, lps, gtoks, glps), last, lens,
                    pin_pool(cache))

        def paged_spec_verify(params, cache, tokens, lengths, temps,
                              rng, adapter_ids, tables):
            """Speculative verify over the block pool (see spec_verify
            for the accept contract)."""
            cache = pin_pool(cache)
            k = tokens.shape[1]
            positions = lengths[:, None] + jnp.arange(k)[None]
            logits, cache = model.apply(params, tokens, positions, cache,
                                        **pkw(tables),
                                        **akw(adapter_ids))
            greedy = jnp.argmax(logits, axis=-1)
            temps_safe = jnp.maximum(temps, 1e-4)[:, None, None]
            sampled = jax.random.categorical(rng, logits / temps_safe,
                                             axis=-1)
            preds = jnp.where(temps[:, None] > 0, sampled,
                              greedy).astype(jnp.int32)
            preds_lp = chosen_logprob(logits, preds)
            t_ids, t_lps = topk_lp(logits)
            return (pack_head(preds, preds_lp, t_ids, t_lps),
                    pin_pool(cache))

        def paged_copy_blocks(cache, src, dsts):
            """Copy pool block `src` into every block of dsts [G], per
            layer — the one device op a prefix hit pays (privatizing
            the partial tail block; the full blocks are shared by
            table reference).  Pad dsts entries may repeat a real dst:
            duplicate scatters write identical bytes."""
            new = []
            for kp, vp in pin_pool(cache):
                kb = jnp.broadcast_to(kp[src][None],
                                      (dsts.shape[0],) + kp.shape[1:])
                vb = jnp.broadcast_to(vp[src][None],
                                      (dsts.shape[0],) + vp.shape[1:])
                new.append((kp.at[dsts].set(kb), vp.at[dsts].set(vb)))
            return pin_pool(new)

        def paged_restore_blocks(cache, dsts, krows, vrows):
            """Scatter host-restored rows into pool blocks dsts [G]:
            krows/vrows [L, G, Hkv, bs, D] carry one tier entry per
            real dst (the host-tier restore / hot-set adoption path).
            Dispatched ASYNC before the suffix prefill, so the
            host->device transfer and scatter hide behind compute the
            request needs anyway.  Pad dsts entries repeat a real dst
            with identical rows: duplicate scatters are idempotent."""
            new = []
            for li, (kp, vp) in enumerate(pin_pool(cache)):
                new.append((kp.at[dsts].set(krows[li]),
                            vp.at[dsts].set(vrows[li])))
            return pin_pool(new)

        self._paged_prefill = jax.jit(paged_prefill, donate_argnums=(4,),
                                      static_argnums=(9,))
        self._paged_decode = jax.jit(paged_decode, donate_argnums=(1,),
                                     static_argnums=(8,))
        self._paged_spec_verify = jax.jit(paged_spec_verify,
                                          donate_argnums=(1,))
        self._paged_copy_blocks = jax.jit(paged_copy_blocks,
                                          donate_argnums=(0,))
        self._paged_restore_blocks = jax.jit(paged_restore_blocks,
                                             donate_argnums=(0,))
        self._prefill_insert = jax.jit(prefill_insert, donate_argnums=(4,),
                                       static_argnums=(9,))
        self._chunk_prefill = jax.jit(chunk_prefill, donate_argnums=(4,))
        self._decode = jax.jit(decode, donate_argnums=(1,),
                               static_argnums=(7,))
        self._spec_verify = jax.jit(spec_verify, donate_argnums=(1,))
        self._prefill_capture = jax.jit(prefill_capture)
        # start is traced (dynamic): compiles key on (pow2_floor(start),
        # suffix bucket) SHAPES only — see prefix_prefill's docstring.
        self._prefix_prefill = jax.jit(prefix_prefill,
                                       donate_argnums=(6,))

    # ----------------------------------------------------- paged allocator
    #
    # The allocator itself lives in infer/block_pool.py (BlockPool);
    # the engine keeps thin delegates (the scheduling code and the
    # skycheck block pass track these call sites) and read-only
    # property views of the pool's arrays under their historical names
    # (the conservation sanitizer and the paged tests audit through
    # them).  All still guarded by _lock.

    @property
    def _block_refs(self):
        return self._pool._block_refs

    @property
    def _tables_np(self):
        return self._pool._tables_np

    @property
    def _slot_nblocks(self):
        return self._pool._slot_nblocks

    @property
    def _free_blocks(self):
        return self._pool._free_blocks

    @property
    def _num_blocks(self) -> int:
        return self._pool._num_blocks if self._pool is not None else 0

    @property
    def _max_blocks(self) -> int:
        return self._pool._max_blocks if self._pool is not None else 1

    def _nb_bucket(self, needed: int) -> int:
        """Table width (in blocks) for a dispatch: the smallest power
        of two >= needed, capped at max_blocks — the gather width is
        bucketed so compiles stay O(log(max_blocks)) per dispatch
        shape instead of one per block count."""
        nb = 1
        while nb < needed and nb < self._max_blocks:
            nb *= 2
        return min(nb, self._max_blocks)

    def _alloc_blocks(self, k: int) -> List[int]:  # locked: _lock
        return self._pool._alloc_blocks(k)

    def _deref_block(self, b: int) -> None:  # locked: _lock
        self._pool._deref_block(b)

    def _addref_block(self, b: int) -> None:  # locked: _lock
        self._pool._addref_block(b)

    def _evict_radix(self, need: int) -> int:  # locked: _lock
        """Evict unpinned radix LEAVES whose only reference is the
        tree's own (so the deref actually frees a block), LRU-first,
        until `need` blocks freed or nothing evictable remains.
        Cascades as parents become leaves.  Caller holds the lock.

        With the host tier armed, each victim's rows are spilled to
        host RAM first (recency-gated, async) — the block id is still
        freed here, so admission headroom is unchanged; only the rows
        survive, to be restored into FRESH blocks on the next match."""
        on_evict = (self._spill_blocks if self._host_tier is not None
                    else None)
        freed = self._radix.evict(need, self._block_refs,
                                  self._deref_block, on_evict=on_evict)
        self.radix_stats['evictions'] += freed
        return freed

    def _spill_blocks(self, adapter: Optional[str], node) -> None:  # locked: _lock
        """Radix-eviction spill hook: snapshot the victim block's rows
        into the host tier BEFORE the deref recycles the block id.
        The per-layer slices are fresh device buffers (not views into
        the donated pool), so later pool-donating dispatches cannot
        invalidate the in-flight host copy.  Dead-cold victims (not
        referenced within the tier's recency window) skip the copy —
        they were evicted because nobody wants them."""
        tier = self._host_tier
        if (self._radix.clock - node.last_used) > tier.recency_window:
            return
        tokens = RadixTree.path_tokens(node)
        blk = int(node.block)
        ks = [kp[blk] for kp, _ in self.cache]
        vs = [vp[blk] for _, vp in self.cache]
        tier.spill((adapter, tokens), ks, vs)

    def _ensure_blocks(self, slot: int, upto: int) -> None:  # locked: _lock
        self._pool._ensure_blocks(slot, upto)

    def _append_shared_blocks(self, slot: int,  # locked: _lock
                              ids: Sequence[int]) -> None:
        self._pool._append_shared_blocks(slot, ids)

    def _free_slot_blocks(self, slot: int) -> None:  # locked: _lock
        self._pool._free_slot_blocks(slot)

    # ------------------------------------------------------- host KV tier

    def _restore_from_tier(self, req: Request, blocks: List[int],
                           n: int) -> List[int]:  # locked: _lock
        """Extend a radix match with blocks restored from the host
        tier: probe successive block-aligned prefixes past the device
        match, pop the hits, scatter their rows into freshly allocated
        pool blocks (one async dispatch), and index them in the radix
        tree — the caller then treats the extended match like any
        other radix hit, so the restore transfer overlaps the
        suffix-only prefill it just shortened.

        Admission safety: the k restored blocks are appended to the
        requesting slot's table by the radix-group start, substituting
        one-for-one for private blocks the slot's admitted worst-case
        demand already reserved — free-list headroom backing OTHER
        running slots' reservations is untouched."""
        tier = self._host_tier
        bs_ = self.cfg.kv_block_size
        limit = (n - 1) // bs_       # >= 1 suffix token must forward
        keys: List[Any] = []
        while len(blocks) + len(keys) < limit:
            j = len(blocks) + len(keys)
            key = (req.adapter,
                   tuple(int(t) for t in req.tokens[:(j + 1) * bs_]))
            if not tier.contains(key):
                break
            keys.append(key)
        # Restore only when a suffix bucket still fits beside the
        # extended match — otherwise the request would fall back to
        # full prefill and strand the fresh blocks in the tree,
        # breaking the one-for-one demand substitution above.
        while keys:
            start = (len(blocks) + len(keys)) * bs_
            if (self._suffix_bucket(start, n - start) is not None
                    and len(keys) <= len(self._free_blocks)):
                break
            keys.pop()
        if not keys:
            return blocks
        rows = [tier.take(k) for k in keys]
        if any(r is None for r in rows):   # unreachable under _lock
            return blocks
        end = (len(blocks) + len(keys)) * bs_
        ids = self._adopt_host_rows(req.adapter, req.tokens[:end],
                                    blocks, rows)
        tier.stats['restores'] += len(ids)
        return list(blocks) + ids

    def _adopt_host_rows(self, adapter: Optional[str],  # locked: _lock
                         tokens: Sequence[int],
                         base_blocks: Sequence[int],
                         rows) -> List[int]:
        """Allocate pool blocks for host-serialized rows (tier restore
        or hot-set adoption), scatter them in with ONE async
        paged_restore_blocks dispatch, and index them in the radix
        tree as the continuation of ``base_blocks``.  ``rows`` is a
        list of (k, v) numpy pairs, each [L, Hkv, bs, D] in cache
        dtype — the topology-neutral host form; device_put re-shards
        them under this replica's mesh whatever the exporter's tp."""
        bs_ = self.cfg.kv_block_size
        hkv = self.model_config.num_kv_heads
        hd = self.model_config.head_dim_
        nl = len(self.cache)  # compile-shape: nl=const
        dt = np.dtype(self.cfg.cache_dtype)
        k = len(rows)
        g = self._nb_bucket(k)
        ids = self._alloc_blocks(k)  # owns-blocks: radix
        try:
            dsts = np.zeros((g,), np.int32)  # jit-ok: g = _nb_bucket(k), pow2-bucketed
            kbuf = np.zeros((nl, g, hkv, bs_, hd), dt)  # jit-ok: g bucketed
            vbuf = np.zeros((nl, g, hkv, bs_, hd), dt)  # jit-ok: g bucketed
            for i in range(g):
                # Pad lanes repeat the last real entry: duplicate
                # scatters of identical rows are idempotent.
                j = min(i, k - 1)
                dsts[i] = ids[j]
                kbuf[:, i] = rows[j][0]
                vbuf[:, i] = rows[j][1]
            kdev = jax.device_put(kbuf, self._rows_sharding)
            vdev = jax.device_put(vbuf, self._rows_sharding)
            with self._ctx():
                self.cache = self._paged_restore_blocks(
                    self.cache, jnp.asarray(dsts), kdev, vdev)
        except BaseException:
            for b in ids:
                self._deref_block(b)
            raise
        self.radix_stats['inserts'] += self._radix.insert(
            adapter, tokens, list(base_blocks) + ids,
            addref=self._addref_block, deref=self._deref_block,
            own=True)
        return ids

    def _slot_cap_rows(self, n: int, max_new: int) -> int:
        """Worst-case filled rows of a request: prompt + generated
        (the prefill token is generated token #1, so the last decode
        write lands at row n + max_new - 2), capped at the cache."""
        return min(n + max_new - 1, self.cfg.max_cache_len)

    def _blocks_demand(self, n: int, max_new: int) -> int:
        return -(-self._slot_cap_rows(n, max_new) //
                 self.cfg.kv_block_size)

    def _blocks_outstanding(self) -> int:
        """Blocks running slots may still allocate (worst case): their
        total demand minus what they already hold.  Shared prefix
        blocks count as held, so sharing directly raises admission
        headroom."""
        out = 0
        for i, s in enumerate(self._slots):
            if s is not None:
                out += max(0, self._blocks_demand(
                    len(s.request.tokens), s.max_new)
                    - int(self._slot_nblocks[i]))
        for slot, job in self._chunking.items():
            out += max(0, self._blocks_demand(job.n, job.max_new)
                       - int(self._slot_nblocks[slot]))
        return out

    def _can_admit_blocks(self, demand: int, extra: int = 0) -> bool:
        """Admission rule: start a request only when free blocks cover
        its worst-case demand beyond everything running slots (and
        `extra` — demand of requests admitted in the same gap) may
        still claim.  Guarantees _alloc_blocks never fails
        mid-flight."""
        if not self._paged:
            return True
        if self._fault('block_alloc') is not None:
            # Injected pool pressure: answer "no" so the request takes
            # the normal defer path — exhaustion must degrade to
            # queueing, never to a crash.
            return False
        short = (demand + self._blocks_outstanding() + extra
                 - len(self._free_blocks))
        if short > 0 and self._radix is not None:
            # Cached-but-unreferenced radix blocks are reclaimable
            # capacity, not load: shed leaves BEFORE deferring the
            # request (a request must never queue behind cache).
            self._evict_radix(short)
        return (len(self._free_blocks) - self._blocks_outstanding()
                - extra >= demand)

    def _force_admit_blocks(self, demand: int) -> bool:
        """Last resort when a request can't be admitted and NOTHING is
        running (offline batch with prefix entries hogging the pool):
        LRU-evict prefix entries until the request fits.  With no
        running slot every shared ref is entry-held, so eviction
        actually frees blocks.  Returns admissibility."""
        while (not self._can_admit_blocks(demand) and self._prefixes and
               not any(s is not None for s in self._slots) and
               not self._chunking):
            _, entry = self._prefixes.popitem(last=False)
            for b in entry['blocks']:
                self._deref_block(b)
        return self._can_admit_blocks(demand)

    def _lane_tables(self, slot_rows: Sequence[int],
                     nb: int) -> jnp.ndarray:
        """Device table array for a dispatch: the named slots' table
        rows, truncated/padded to `nb` entries (entries past a slot's
        allocation are 0 = the dump block)."""
        rows = self._tables_np[np.asarray(slot_rows, np.int32)]
        if nb <= rows.shape[1]:
            rows = rows[:, :nb]
        else:
            rows = np.pad(rows, ((0, 0), (0, nb - rows.shape[1])))
        return jnp.asarray(rows)

    def _radix_section(self) -> Dict[str, Any]:
        rs = self.radix_stats
        lookups = rs['lookups']
        return {
            'enabled': self._radix is not None,
            'hits': rs['hits'],
            'lookups': lookups,
            'hit_rate': (rs['hits'] / lookups) if lookups else 0.0,
            'tokens_reused': rs['tokens_reused'],
            'inserts': rs['inserts'],
            'evictions': rs['evictions'],
            'nodes': self._radix.nodes if self._radix else 0,
            'blocks_held': (self._radix.blocks_held
                            if self._radix else 0),
            'pinned': self._radix.pinned if self._radix else 0,
        }

    def _host_tier_section(self) -> Dict[str, Any]:
        """kv.host_tier for kv_health()/stats(): one key set whether
        the tier exists or not, so wire consumers never key-miss on a
        tierless replica.  Lock-free counter reads, like the rest."""
        hs = self.handoff_stats
        t = self._host_tier
        if t is None:
            sec = {
                'enabled': False,
                'budget_bytes': 0,
                'bytes': 0,
                'entries': 0,
                'spills': 0,
                'restores': 0,
                'restore_hit_rate': 0.0,
                'in_flight': 0,
                'evictions': 0,
            }
        else:
            sec = t.stats_section()
        sec['exported'] = hs['exported']
        sec['adopted'] = hs['adopted']
        return sec

    def export_hot_prefixes(self, max_prefixes: int = 8,
                            max_blocks: int = 64) -> Dict[str, Any]:
        """Serialize the hottest radix prefixes — device tree first
        (still resident = hottest), then host-tier entries, most
        recent first — into the topology-neutral wire form
        adopt_prefixes() accepts: the drain-time hot-set handoff
        payload (GET /hot_prefixes; the LB orchestrates the transfer
        to the affinity-ring survivor during drain).  Blocking
        device→host gathers, so this belongs on the drain path, not
        the serving fast path."""
        import base64
        payload: Dict[str, Any] = {
            'version': 1,
            'model': self.cfg.model,
            'block_size': self.cfg.kv_block_size,
            'cache_dtype': np.dtype(self.cfg.cache_dtype).name,
            'num_layers': len(self.cache),
            'prefixes': [],
        }
        if self._radix is None:
            return payload
        bs_ = self.cfg.kv_block_size
        with self._lock:
            cands = []
            leaves = [(ad, nd)
                      for ad, nd in self._radix.walk_adapters()
                      if not nd.children]
            leaves.sort(key=lambda x: -x[1].last_used)
            for ad, nd in leaves:
                cands.append((ad, RadixTree.path_tokens(nd)))
            if self._host_tier is not None:
                cands.extend(self._host_tier.keys_recent_first())
            # Drop candidates subsumed by an earlier (hotter) one.
            chosen: List[Tuple[Optional[str], Tuple[int, ...]]] = []
            for ad, toks in cands:
                if len(chosen) >= max_prefixes:
                    break
                if any(a == ad and t[:len(toks)] == toks
                       for a, t in chosen):
                    continue
                chosen.append((ad, toks))
            budget = max_blocks
            for ad, toks in chosen:
                if budget <= 0:
                    break
                path = self._radix.peek(ad, toks, len(toks))
                recs = []
                for i in range(min(len(toks) // bs_, budget)):
                    if i < len(path):
                        # Device-resident: gather the block's global
                        # rows across chips.
                        blk = int(path[i])
                        k_rows = np.stack([np.asarray(kp[blk])
                                           for kp, _ in self.cache])
                        v_rows = np.stack([np.asarray(vp[blk])
                                           for _, vp in self.cache])
                    else:
                        entry = (self._host_tier.get(
                            (ad, toks[:(i + 1) * bs_]))
                            if self._host_tier is not None else None)
                        if entry is None:
                            break   # hole: a prefix must be contiguous
                        k_rows, v_rows = entry
                    recs.append({
                        'k': base64.b64encode(
                            k_rows.tobytes()).decode('ascii'),
                        'v': base64.b64encode(
                            v_rows.tobytes()).decode('ascii'),
                    })
                if not recs:
                    continue
                budget -= len(recs)
                payload['prefixes'].append({
                    'adapter': ad,
                    'tokens': [int(t) for t in toks[:len(recs) * bs_]],
                    'blocks': recs,
                })
            self.handoff_stats['exported'] += sum(
                len(p['blocks']) for p in payload['prefixes'])
        return payload

    def adopt_prefixes(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Adopt a drained peer's serialized hot prefixes into this
        engine's radix tree (the POST /adopt_blocks body): mid-stream
        failover and scale-down then cost a suffix-only prefill
        instead of a full re-prefill.  Topology-neutral: the rows
        re-shard under THIS replica's mesh regardless of the
        exporter's tp degree.  Payload-level mismatches raise
        ValueError (a client error); per-prefix problems skip."""
        import base64
        if self._radix is None:
            raise ValueError('replica has no radix cache '
                             '(kv_block_size/auto_prefix_cache off)')
        if int(payload.get('version', 0)) != 1:
            raise ValueError(
                f"unsupported hot-prefix payload version "
                f"{payload.get('version')!r}")
        bs_ = self.cfg.kv_block_size
        if int(payload.get('block_size', 0)) != bs_:
            raise ValueError(
                f"block_size mismatch: payload "
                f"{payload.get('block_size')!r}, engine {bs_}")
        dt = np.dtype(self.cfg.cache_dtype)
        if payload.get('cache_dtype') != dt.name:
            raise ValueError(
                f"cache_dtype mismatch: payload "
                f"{payload.get('cache_dtype')!r}, engine {dt.name}")
        if payload.get('model') != self.cfg.model:
            raise ValueError(
                f"model mismatch: payload {payload.get('model')!r}, "
                f"engine {self.cfg.model!r}")
        nl = len(self.cache)
        if int(payload.get('num_layers', 0)) != nl:
            raise ValueError(
                f"num_layers mismatch: payload "
                f"{payload.get('num_layers')!r}, engine {nl}")
        mc = self.model_config
        row_shape = (nl, mc.num_kv_heads, bs_, mc.head_dim_)
        adopted_p = adopted_b = skipped = 0
        with self._lock:
            for pref in payload.get('prefixes', []):
                tokens = [int(t) for t in pref.get('tokens', [])]
                adapter = pref.get('adapter')
                if (adapter is not None
                        and adapter not in self._adapter_names):
                    skipped += 1
                    continue
                try:
                    rows = []
                    for rec in pref.get('blocks', []):
                        k_rows = np.frombuffer(
                            base64.b64decode(rec['k']),
                            dt).reshape(row_shape)
                        v_rows = np.frombuffer(
                            base64.b64decode(rec['v']),
                            dt).reshape(row_shape)
                        rows.append((k_rows, v_rows))
                except (KeyError, TypeError, ValueError):
                    skipped += 1
                    continue
                nruns = min(len(rows), len(tokens) // bs_)
                if nruns < 1:
                    skipped += 1
                    continue
                existing = self._radix.match(adapter, tokens,
                                             nruns * bs_)
                rows = rows[len(existing):nruns]
                if not rows:
                    continue            # already resident
                # Adopted blocks are cache, not load: never eat into
                # free-list headroom running slots' admission already
                # reserved (a mid-flight _alloc_blocks must not fail).
                headroom = (len(self._free_blocks)
                            - self._blocks_outstanding())
                rows = rows[:max(0, headroom)]
                if not rows:
                    skipped += 1
                    continue
                end = (len(existing) + len(rows)) * bs_
                ids = self._adopt_host_rows(adapter, tokens[:end],
                                            existing, rows)
                adopted_p += 1
                adopted_b += len(ids)
            self.handoff_stats['adopted'] += adopted_b
        return {'adopted_prefixes': adopted_p,
                'adopted_blocks': adopted_b, 'skipped': skipped}

    @property
    def serving(self) -> bool:
        """True while the continuous-batching serving loop is alive
        (generate_stream's supervisor holds its run region).  False
        before the loop starts, after a clean stop, and — the case the
        replica /healthz endpoint exists for — after the supervisor
        gave up on a crash-looping serve loop."""
        return self._serving

    def kv_health(self) -> Dict[str, Any]:
        """Cheap KV/prefix-cache summary for the /healthz payload.

        The serve-plane LB probes /healthz on a short interval from a
        routing-critical thread, so unlike stats() this avoids the
        numpy refcount scans: counters only.  prefix_affinity routing
        reads block_size (route-key run length), occupancy (cache-full
        load penalty) and radix.hit_rate (affinity load-bound boost)."""
        rs = self.radix_stats
        lookups = rs['lookups']
        radix = {
            'enabled': self._radix is not None,  # wire-ok: operator dashboard field
            'hits': rs['hits'],  # wire-ok: operator dashboard field
            'lookups': lookups,  # wire-ok: operator dashboard field
            'hit_rate': (rs['hits'] / lookups) if lookups else 0.0,
            'nodes': self._radix.nodes if self._radix else 0,  # wire-ok: operator dashboard field
            'evictions': rs['evictions'],  # wire-ok: operator dashboard field
        }
        if not self._paged:
            # Same key set as the paged branch: prefix_affinity keys
            # its route length off block_size and the LB caches this
            # document per replica — a dense replica in a mixed fleet
            # must not make consumers key-miss (block_size 0 reads as
            # "no paged pool", observe_replica ignores it).
            return {
                'layout': 'dense',
                'block_size': 0,
                'blocks_total': 0,
                'blocks_free': 0,
                'occupancy': 0.0,
                'tp': self._tp,
                'radix': radix,
                'host_tier': self._host_tier_section(),
            }
        usable = self._num_blocks - 1
        free = len(self._free_blocks)
        return {
            'layout': 'paged',  # wire-ok: operator dashboard field
            'block_size': self.cfg.kv_block_size,
            'blocks_total': usable,  # wire-ok: operator dashboard field
            'blocks_free': free,  # wire-ok: operator dashboard field
            'occupancy': ((usable - free) / usable) if usable else 0.0,
            'tp': self._tp,
            'radix': radix,
            'host_tier': self._host_tier_section(),
        }

    def stats(self) -> Dict[str, Any]:
        """KV-cache accounting (served by /stats).  Everything lives
        under ONE structured 'kv' section — layout, blocks, bytes,
        prefix + radix caching, admission — while the historical flat
        keys (kv_layout, kv_bytes_*, blocks_*, admission_deferred,
        prefix_block_hits, ...) remain as DEPRECATED aliases so
        existing dashboards keep reading."""
        mc = self.model_config
        row_bytes = (2 * mc.num_kv_heads * mc.head_dim_ *
                     np.dtype(self.cfg.cache_dtype).itemsize *
                     mc.num_layers)
        prefix = {**self.prefix_stats,
                  'resident': len(self._prefixes)}
        radix = self._radix_section()
        # The cache is head-sharded over the tensor axis (dense and
        # paged alike), so each chip holds bytes ÷ tp: the per_chip_*
        # keys are the numbers HBM capacity planning needs — reporting
        # global pool bytes as if every chip held them would overstate
        # occupancy by the tensor degree.
        tp = self._tp
        if not self._paged:
            total = self.cfg.num_slots * self.cfg.max_cache_len
            kv = {
                'layout': 'dense',
                'tp': tp,
                'bytes': {'total': total * row_bytes,
                          'resident': total * row_bytes,
                          'per_chip_total': total * row_bytes // tp,
                          'per_chip_resident': total * row_bytes // tp},
                'prefix': prefix,
                'radix': radix,
                'host_tier': self._host_tier_section(),
            }
            return {
                'kv': kv,
                'serving': bool(self._serving),
                # deprecated aliases of kv.* — the SAME key set as the
                # paged branch (zeros where dense has no block pool):
                # dashboards and tests read these flat keys without
                # knowing which layout the replica runs.
                'kv_layout': 'dense',
                'block_size': 0,
                'blocks_total': 0,
                'blocks_free': 0,
                'blocks_allocated': 0,
                'blocks_shared': 0,
                'blocks_prefix': 0,
                'shared_refs_saved': 0,
                'kv_bytes_per_block': 0,
                'kv_bytes_total': total * row_bytes,
                'kv_bytes_resident': total * row_bytes,
                'admission_deferred': 0,
                'prefix_block_hits': 0,
                'faults': dict(self.fault_stats),
                'qos': self._qos_section(),
            }
        bs_ = self.cfg.kv_block_size
        block_bytes = bs_ * row_bytes
        usable = self._num_blocks - 1
        free = len(self._free_blocks)
        refs = self._block_refs
        shared = int((refs[1:] > 1).sum())
        prefix_blocks = sum(len(e['blocks'])
                            for e in self._prefixes.values())
        prefix['block_hits'] = self.paged_stats['prefix_block_hits']
        prefix['blocks'] = prefix_blocks
        kv = {
            'layout': 'paged',
            'tp': tp,
            'blocks': {
                'size': bs_,
                'total': usable,
                'free': free,
                'allocated': usable - free,
                'shared': shared,
                # Table entries resolved by sharing instead of
                # allocation (refcounts beyond each block's first).
                'shared_refs_saved':
                    int((refs[1:][refs[1:] > 1] - 1).sum()),
            },
            'bytes': {
                'per_block': int(block_bytes),
                'total': int(self._num_blocks * block_bytes),
                'resident': int((usable - free) * block_bytes),
                'per_chip_total':
                    int(self._num_blocks * block_bytes) // tp,
                'per_chip_resident':
                    int((usable - free) * block_bytes) // tp,
            },
            'admission': {'deferred': self.paged_stats['deferred']},
            'prefix': prefix,
            'radix': radix,
            'host_tier': self._host_tier_section(),
        }
        return {
            'kv': kv,
            'serving': bool(self._serving),  # wire-ok: external monitoring field
            # deprecated aliases of kv.*
            'kv_layout': 'paged',
            'block_size': bs_,  # wire-ok: deprecated alias, external readers
            'blocks_total': usable,
            'blocks_free': free,
            'blocks_allocated': usable - free,
            'blocks_shared': shared,
            'blocks_prefix': prefix_blocks,
            'shared_refs_saved': kv['blocks']['shared_refs_saved'],
            'kv_bytes_per_block': int(block_bytes),  # wire-ok: deprecated alias, external readers
            'kv_bytes_total': int(self._num_blocks * block_bytes),  # wire-ok: deprecated alias, external readers
            'kv_bytes_resident': int((usable - free) * block_bytes),  # wire-ok: deprecated alias, external readers
            'admission_deferred': self.paged_stats['deferred'],
            'prefix_block_hits': self.paged_stats['prefix_block_hits'],
            'faults': dict(self.fault_stats),  # wire-ok: external monitoring field
            'qos': self._qos_section(),
        }

    def _qos_section(self) -> Dict[str, Any]:
        """stats()['qos']: scheduler depths, preemption/shed counters,
        per-tenant admitted/shed, and the shed bound's rate estimate.
        Lock-free reads like the rest of stats() (counters race
        benignly; the scheduler snapshots under its own lock)."""
        return {
            'enabled': bool(self.cfg.qos),
            'scheduler': self._sched.stats(),
            'preemptions': self.qos_stats['preemptions'],
            'sheds': self.qos_stats['sheds'],
            'service_rate_tokens_per_s': self._svc_estimator.rate(),
            'tenants': {t: dict(c)
                        for t, c in list(self._tenant_qos.items())},
        }

    # Per-tenant counter rows are bounded: a scraper with unbounded
    # distinct tenant ids must not grow engine memory without limit.
    _MAX_TENANT_ROWS = 256

    def _tenant_row(self, tenant: Optional[str]) -> Dict[str, int]:  # locked: _lock
        t = tenant or qos_mod.DEFAULT_TENANT
        row = self._tenant_qos.get(t)
        if row is None:
            if len(self._tenant_qos) >= self._MAX_TENANT_ROWS:
                t = '_overflow'
            row = self._tenant_qos.setdefault(
                t, {'admitted': 0, 'shed': 0})
        return row

    # ---------------------------------------------------------- schedule

    def _bucket(self, n: int) -> int:
        for b in self.cfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f'prompt length {n} exceeds largest prefill bucket '
            f'{self.cfg.prefill_buckets[-1]}')

    def _free_slot(self, exclude=()) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None and i not in exclude and i not in self._chunking:
                return i
        return None

    def has_free_slot(self) -> bool:
        """Lock-free saturation peek for admission control: a free slot
        means arrivals are NOT queueing (benign race — a stale answer
        only shifts one admission decision by one loop gap).  A slot
        mid-chunked-prefill is occupied, not free."""
        return any(s is None and i not in self._chunking
                   for i, s in enumerate(self._slots))

    def _max_new(self, req: Request) -> int:
        return self.cfg.max_new_tokens if req.max_new_tokens is None \
            else req.max_new_tokens

    def _validate_request(self,
                          req: Request) -> Tuple[int, Optional[int], int]:
        """Returns (prompt_len, bucket, max_new); raises ValueError on a
        bad request.  bucket is None when no configured bucket holds the
        prompt but chunked prefill (cfg.prefill_chunk) can: such prompts
        are accepted up to max_cache_len - max_new and always take the
        chunked path."""
        n = len(req.tokens)
        max_new = self._max_new(req)
        if n < 1:
            raise ValueError('empty prompt')
        if req.adapter is not None:
            if not self.cfg.lora_rank:
                raise ValueError(
                    f'request names adapter {req.adapter!r} but the '
                    'engine was built without lora_rank')
            if req.adapter not in self._adapter_names:
                raise ValueError(
                    f'unknown adapter {req.adapter!r}; registered: '
                    f'{sorted(self._adapter_names)}')
        if max_new < 1:
            raise ValueError(
                f'max_new_tokens must be >= 1 (got {max_new}); generation '
                'always produces at least the prefill token')
        if req.deadline_s is not None and req.deadline_s <= 0:
            raise ValueError(
                f'deadline_s must be > 0 (got {req.deadline_s})')
        if req.priority is not None and \
                req.priority not in qos_mod.PRIORITY_CLASSES:
            raise ValueError(
                f'unknown priority {req.priority!r}; expected one of '
                f'{list(qos_mod.PRIORITY_CLASSES)}')
        try:
            bucket: Optional[int] = self._bucket(n)
        except ValueError:
            if not self.cfg.prefill_chunk:
                raise
            if req.want_prompt_logprobs:
                # Prompt scoring needs EVERY prompt position's logits in
                # one forward; chunked prefill discards non-final chunk
                # logits.
                raise ValueError(
                    f'prompt_logprobs requires the prompt ({n}) to fit '
                    f'the largest prefill bucket '
                    f'({self.cfg.prefill_buckets[-1]})')
            bucket = None
        if n + max_new > self.cfg.max_cache_len:
            raise ValueError(
                f'prompt ({n}) + max_new_tokens ({max_new}) exceeds cache '
                f'({self.cfg.max_cache_len})')
        if self._paged:
            demand = self._blocks_demand(n, max_new)
            usable = self._num_blocks - 1
            if demand > usable:
                raise ValueError(
                    f'request needs {demand} KV blocks but the pool '
                    f'only has {usable} (kv_blocks too small for this '
                    'prompt + max_new_tokens)')
        return n, bucket, max_new

    def _should_chunk(self, req: Request, n: int,
                      bucket: Optional[int]) -> bool:
        """Chunked-prefill policy.  A prompt no bucket holds MUST chunk
        (that is how it got admitted).  In-bucket prompts chunk only in
        the SERVING loop, only when longer than one chunk, and only when
        someone would actually stall behind a monolithic prefill (an
        active slot, or another prompt already chunking) — offline
        batch throughput wants the one-dispatch prefill, and so does a
        prompt arriving to an idle engine (chunking it would only slow
        its own TTFT)."""
        c = self.cfg.prefill_chunk
        if not c or req.want_prompt_logprobs:
            return False
        if bucket is None:
            return True
        return (self._serving and n > c and
                (any(s is not None for s in self._slots) or
                 bool(self._chunking)))

    # --------------------------------------------------------- multi-LoRA

    def _adapter_id(self, req: Request) -> int:
        return (-1 if req.adapter is None
                else self._adapter_names[req.adapter])

    def register_adapter(self, name: str, adapter_tree) -> int:
        """Load a trained LoRA adapter (the `*_lora` subtree produced by
        train/lora.py — see save_adapter_npz) into a stack slot; later
        Requests naming it decode with its delta applied.  Re-registering
        a name overwrites its slot.  Returns the slot index."""
        if not self.cfg.lora_rank:
            raise ValueError(
                'engine built without lora_rank; pass '
                'InferConfig(lora_rank=...) / --lora-rank to serve '
                'adapters')

        import flax.linen as nn

        def walk(tree, sub, path=''):
            out = dict(tree)
            for k, v in sub.items():
                if k not in tree:
                    raise KeyError(
                        f'adapter param {path}{k!r} has no target in the '
                        'model tree (wrong family/targets?)')
                if isinstance(v, dict):
                    out[k] = walk(tree[k], v, f'{path}{k}/')
                else:
                    leaf = tree[k]           # stacked [N, ...]
                    boxed = isinstance(leaf, nn.meta.AxisMetadata)
                    val = leaf.unbox() if boxed else leaf
                    arr = jnp.asarray(np.asarray(v), val.dtype)
                    if arr.shape != val.shape[1:]:
                        raise ValueError(
                            f'adapter leaf {path}{k} shape {arr.shape} '
                            f'does not match model {val.shape[1:]} '
                            '(rank mismatch?)')
                    new = val.at[idx].set(arr)
                    out[k] = leaf.replace_boxed(new) if boxed else new
            return out

        if isinstance(adapter_tree, dict) and \
                set(adapter_tree) == {'params'}:
            adapter_tree = adapter_tree['params']   # tolerate the wrapper
        with self._lock:
            idx = self._adapter_names.get(name)
            if idx is None:
                if len(self._adapter_names) >= self.cfg.lora_max_adapters:
                    raise ValueError(
                        f'adapter slots full '
                        f'({self.cfg.lora_max_adapters}); re-register an '
                        'existing name to replace it')
                idx = len(self._adapter_names)
            inner = walk(self.params['params'], adapter_tree)
            self.params = {**self.params, 'params': inner}
            self._adapter_names[name] = idx
            # Prefix KV computed under this adapter's OLD weights is
            # now stale — matching it would silently produce output
            # inconsistent with a full prefill under the new weights.
            for key in [k for k in self._prefixes if k[0] == name]:
                entry = self._prefixes.pop(key)
                if self._paged:
                    for b in entry['blocks']:
                        self._deref_block(b)
        return idx

    @property
    def adapters(self) -> Dict[str, int]:
        return dict(self._adapter_names)

    # ------------------------------------------------------- prefix cache

    def register_prefix(self, tokens: Sequence[int],
                        adapter: Optional[str] = None) -> int:
        """Compute and keep a prefix's KV rows on device; later prompts
        starting with these tokens prefill only their suffix.  Returns
        the prefix length.  LRU-evicts past cfg.max_prefixes.

        adapter: compute (and match) the rows under that LoRA adapter —
        prefix KV is adapter-dependent, so entries only ever match
        requests naming the same adapter (None = base model)."""
        if not self.cfg.max_prefixes:
            raise ValueError('prefix caching disabled (max_prefixes=0)')
        n = len(tokens)
        if n < 1:
            raise ValueError('empty prefix')
        if adapter is not None and adapter not in self._adapter_names:
            raise ValueError(f'unknown adapter {adapter!r}')
        aid = (-1 if adapter is None
               else self._adapter_names[adapter])
        bucket = self._bucket(n)   # raises when no bucket can hold it
        arr = np.zeros((1, bucket), np.int32)
        arr[0, :n] = tokens
        if self._paged:
            if self._radix is not None:
                return self._register_prefix_radix(arr, n, bucket,
                                                   adapter, aid, tokens)
            return self._register_prefix_paged(arr, n, bucket, adapter,
                                               aid, tokens)
        pcache = init_cache(self.model_config, 1, bucket,
                            self.cfg.cache_dtype)
        # The capture forward (and its first-call trace/compile, which
        # can take tens of seconds on a real model) reads only
        # self.params — run it OUTSIDE the engine lock so in-flight
        # decode keeps producing tokens; only the registry insert needs
        # mutual exclusion.
        with self._ctx():
            pc = self._prefill_capture(self.params, jnp.asarray(arr),
                                       pcache,
                                       jnp.full((1,), aid, jnp.int32))
        kv = [(k[0, :, :n], v[0, :, :n]) for k, v in pc]
        if self._mesh is not None:
            # Rows shard like the cache: kv heads over 'tensor'.
            from skypilot_tpu.parallel import mesh as mesh_lib
            sh = mesh_lib.named_sharding(self._mesh, 'kv_heads', None,
                                         None)
            kv = [(jax.device_put(k, sh), jax.device_put(v, sh))
                  for k, v in kv]
        key = (adapter, tuple(int(t) for t in tokens))
        with self._lock:
            self._prefixes[key] = kv
            self._prefixes.move_to_end(key)
            while len(self._prefixes) > self.cfg.max_prefixes:
                self._prefixes.popitem(last=False)
        return n

    def _register_prefix_paged(self, arr, n, bucket, adapter, aid,
                               tokens) -> int:
        """Paged prefix capture: forward the prefix over the live pool
        into freshly allocated blocks; the entry holds one refcount on
        each.  Later hits SHARE the full blocks (refcount bump, no
        copy).  Runs UNDER the engine lock — unlike the dense capture
        (which only reads params), this writes the shared pool."""
        bs_ = self.cfg.kv_block_size
        need = -(-n // bs_)
        key = (adapter, tuple(int(t) for t in tokens))
        with self._lock:
            def headroom():
                return (len(self._free_blocks)
                        - self._blocks_outstanding())

            # Evict LRU entries first (their blocks free immediately
            # unless a running slot still shares them).
            while headroom() < need and self._prefixes:
                _, old = self._prefixes.popitem(last=False)
                for b in old['blocks']:
                    self._deref_block(b)
            if headroom() < need:
                raise ValueError(
                    f'KV block pool too small to register a {n}-token '
                    f'prefix ({need} blocks; {len(self._free_blocks)} '
                    'free after honoring running slots) — raise '
                    'kv_blocks')
            blocks = self._alloc_blocks(need)  # owns-blocks: entry
            table = np.zeros((1, bucket // bs_), np.int32)
            table[0, :need] = blocks
            try:
                with self._ctx():
                    _, _, self.cache = self._paged_prefill(
                        self.params, jnp.asarray(arr),
                        jnp.zeros((1,), jnp.int32),
                        jnp.full((1,), n - 1, jnp.int32), self.cache,
                        jnp.asarray(table),
                        jnp.zeros((1,), jnp.float32),
                        jax.random.PRNGKey(0),
                        jnp.full((1,), aid, jnp.int32), False)
            except BaseException:
                # The registry never adopted the blocks: return the
                # refs so pool accounting stays balanced (the runtime
                # block sanitizer asserts this at quiesce).
                for b in blocks:
                    self._deref_block(b)
                raise
            self._prefixes[key] = {'blocks': blocks, 'len': n}
            self._prefixes.move_to_end(key)
            while len(self._prefixes) > self.cfg.max_prefixes:
                _, old = self._prefixes.popitem(last=False)
                for b in old['blocks']:
                    self._deref_block(b)
        return n

    def _register_prefix_radix(self, arr, n, bucket, adapter, aid,
                               tokens) -> int:
        """register_prefix under auto_prefix_cache = optional PINNING:
        the prefix's full blocks are prefilled into the pool (or found
        already cached), inserted into the radix tree, and marked
        pinned — eviction-exempt, so a cold-start system prompt stays
        resident under pool pressure instead of churning with the LRU.
        Returns the pinned length, block-aligned (the tree shares
        whole blocks only; a sub-block tail is not cacheable)."""
        bs_ = self.cfg.kv_block_size
        m = (n // bs_) * bs_
        if m < bs_:
            raise ValueError(
                f'prefix shorter than one KV block ({bs_} tokens) '
                'cannot be pinned under auto_prefix_cache')
        need = m // bs_
        with self._lock:
            # _can_admit_blocks sheds unpinned radix leaves first, so
            # pinning displaces cache before it can fail.
            if not self._can_admit_blocks(need):
                raise ValueError(
                    f'KV block pool too small to pin a {n}-token '
                    f'prefix ({need} blocks; {len(self._free_blocks)} '
                    'free after honoring running slots) — raise '
                    'kv_blocks')
            blocks = self._alloc_blocks(need)  # owns-blocks: radix
            table = np.zeros((1, bucket // bs_), np.int32)
            table[0, :need] = blocks
            # Rows [m, n) (the sub-block tail) scatter into table
            # entries past `need`, i.e. the dump block — discarded.
            try:
                with self._ctx():
                    _, _, self.cache = self._paged_prefill(
                        self.params, jnp.asarray(arr),
                        jnp.zeros((1,), jnp.int32),
                        jnp.full((1,), n - 1, jnp.int32), self.cache,
                        jnp.asarray(table),
                        jnp.zeros((1,), jnp.float32),
                        jax.random.PRNGKey(0),
                        jnp.full((1,), aid, jnp.int32), False)
            except BaseException:
                # The tree never adopted the blocks: return the refs
                # so pool accounting stays balanced.
                for b in blocks:
                    self._deref_block(b)
                raise
            # own=True: the tree takes over our allocation refs;
            # duplicates of already-cached runs are dereffed (freed).
            self.radix_stats['inserts'] += self._radix.insert(
                adapter, tokens, blocks, addref=self._addref_block,
                deref=self._deref_block, own=True, pinned=True)
        return m

    def _match_prefix(self, tokens: Sequence[int],
                      adapter: Optional[str] = None):
        """Longest registered prefix FULLY matching the prompt's head
        under the SAME adapter (prefix KV is adapter-dependent).
        Returns (start, key): start = len(prefix) reused rows, or
        len(prefix)-1 when the prompt IS the prefix (one token must
        forward to produce logits).  Prompts lying strictly inside a
        prefix still fall back to full prefill — the dynamic-start
        prefix_prefill no longer compiles per start value, but partial
        matches stay out of scope here (the radix tree is the
        block-granular generalization)."""
        n = len(tokens)
        best = None
        for key in self._prefixes:
            p_adapter, p_tokens = key
            if p_adapter != adapter:
                continue
            lp = len(p_tokens)
            if n > lp:
                if tuple(tokens[:lp]) != p_tokens:
                    continue
                start = lp
            elif n == lp:
                start = lp - 1
                if start < 1 or tuple(tokens[:start]) != p_tokens[:start]:
                    continue
            else:
                continue
            if best is None or start > best[0]:
                best = (start, key)
        if best is None:
            return None
        start, key = best
        self._prefixes.move_to_end(key)          # LRU touch
        return start, key

    def _suffix_bucket(self, start: int, suffix_len: int) -> Optional[int]:
        for b in self.cfg.prefill_buckets:
            if b >= suffix_len and start + b <= self.cfg.max_cache_len:
                return b
        return None

    def _start_prefixed_group(self, group, start: int, sb: int,  # locked: _lock
                              key) -> None:
        """Prefill prefix-matched requests sharing (prefix, start,
        suffix bucket) in lane-batched dispatches — same chunking and
        pad-lane-duplication rules as the normal prefill path."""
        if self._paged:
            self._start_prefixed_group_paged(group, start, sb, key)
            return
        kv = self._prefixes[key]
        adapter, p_tokens = key
        aid = (-1 if adapter is None else self._adapter_names[adapter])
        if start < len(p_tokens):
            # prompt == prefix: all rows but the last (row start..n-1
            # would shadow the one forwarded token).
            kv = [(k[:, :start], v[:, :start]) for k, v in kv]
        # pow2-floor bucketing of the DYNAMIC start: rows [0, b) ride
        # as-is, rows [b, start) are copied into a zero-padded b-wide
        # remainder buffer — the jit key is (b, sb), not start.
        b_ = 1
        while b_ * 2 <= start:
            b_ *= 2
        prefix_b = [(k[:, :b_], v[:, :b_]) for k, v in kv]  # compile-shape: prefix_b=prefix_pow2
        r = start - b_
        rem = []        # compile-shape: rem=prefix_pow2
        for k, v in kv:
            hkv, _, hd = k.shape
            if r:
                zk = jnp.zeros((hkv, b_ - r, hd), k.dtype)
                rem.append((jnp.concatenate([k[:, b_:start], zk], axis=1),
                            jnp.concatenate([v[:, b_:start], zk], axis=1)))
            else:
                rem.append((jnp.zeros((hkv, b_, hd), k.dtype),
                            jnp.zeros((hkv, b_, hd), v.dtype)))
        lanes = self.cfg.prefill_lanes
        for ofs in range(0, len(group), lanes):
            chunk = group[ofs:ofs + lanes]
            p = len(chunk)
            width = lanes
            tokens = np.zeros((width, sb), np.int32)
            true_lens = np.ones((width,), np.int32)
            slots = np.zeros((width,), np.int32)
            temps = np.zeros((width,), np.float32)
            for i in range(width):
                req, slot, _, n, _, _ = chunk[min(i, p - 1)]
                ns = n - start
                tokens[i, :ns] = req.tokens[start:]
                true_lens[i] = ns
                slots[i] = slot
                temps[i] = req.temperature
            # Same pad-lane invariant as _start_batch: duplicated lanes
            # rewrite the SAME slot with byte-identical rows.
            assert all(slots[i] == slots[p - 1]
                       for i in range(p, width)), (
                f'pad lanes must duplicate the last real lane: '
                f'{slots=} p={p}')
            self._rng, rkey = jax.random.split(self._rng)
            with self._ctx():
                head, self.cache = \
                    self._prefix_prefill(
                        self.params, jnp.asarray(tokens),
                        jnp.asarray(start, jnp.int32),
                        jnp.asarray(true_lens), prefix_b, rem,
                        self.cache, jnp.asarray(slots),
                        jnp.asarray(temps), rkey,
                        jnp.full((width,), aid, jnp.int32))
            first_np, first_lp_np, tids, tlps = _unpack_head(
                np.asarray(head), self.cfg.logprob_topk)  # jit-ok: ONE transfer per prefill
            top_np = (tids, tlps)
            now = time.time()
            for i, (req, slot, submit_time, n, _, max_new) in \
                    enumerate(chunk):
                s = _Slot(req, length=n, submit_time=submit_time,
                          max_new=max_new)
                s.first_token_time = now
                s.generated.append(int(first_np[i]))
                s.lps.append(float(first_lp_np[i]))
                s.tops.append(_pairs(top_np[0][i], top_np[1][i]))
                self._slots[slot] = s
                self._lengths[slot] = n
                self._last_tokens[slot] = s.generated[0]
                self._temps[slot] = req.temperature
                self._slot_adapters[slot] = aid
            self.prefix_stats['hits'] += p
            self.prefix_stats['tokens_reused'] += start * p

    def _start_prefixed_group_paged(self, group, start: int, sb: int,  # locked: _lock
                                    key) -> None:
        """Copy-free prefix reuse: each matched slot's table gets the
        prefix's full blocks by REFERENCE (refcount bump — N slots
        share one resident system prompt), a partial tail block is
        privatized with one block copy, and the suffix forwards over
        the pool with a DYNAMIC start (no per-start compile, unlike
        the dense prefix_prefill)."""
        entry = self._prefixes[key]
        adapter, _ = key
        aid = (-1 if adapter is None else self._adapter_names[adapter])
        bs_ = self.cfg.kv_block_size
        shared_n = start // bs_
        tail = start % bs_
        lanes = self.cfg.prefill_lanes
        for ofs in range(0, len(group), lanes):
            chunk = group[ofs:ofs + lanes]
            p = len(chunk)
            width = lanes
            tokens = np.zeros((width, sb), np.int32)
            true_pos = np.zeros((width,), np.int32)
            slots = np.zeros((width,), np.int32)
            temps = np.zeros((width,), np.float32)
            dsts = []
            for req, slot, _, n, _, _ in chunk:   # real lanes only
                self._append_shared_blocks(
                    slot, [int(b) for b in entry['blocks'][:shared_n]])
                if tail:
                    [dst] = self._alloc_blocks(1)  # owns-blocks: table
                    cur = int(self._slot_nblocks[slot])
                    self._tables_np[slot, cur] = dst
                    self._slot_nblocks[slot] = cur + 1
                    dsts.append(dst)
                self._ensure_blocks(slot, n)
                self.paged_stats['prefix_block_hits'] += shared_n
            for i in range(width):
                req, slot, _, n, _, _ = chunk[min(i, p - 1)]
                ns = n - start
                tokens[i, :ns] = req.tokens[start:]
                true_pos[i] = ns - 1
                slots[i] = slot
                temps[i] = req.temperature
            assert all(slots[i] == slots[p - 1]
                       for i in range(p, width)), (
                f'pad lanes must duplicate the last real lane: '
                f'{slots=} p={p}')
            if tail and dsts:
                # One batched copy privatizes every lane's tail block
                # (pad entries repeat the last dst: identical writes).
                darr = np.full((width,), dsts[-1], np.int32)
                darr[:len(dsts)] = dsts
                with self._ctx():
                    self.cache = self._paged_copy_blocks(
                        self.cache, int(entry['blocks'][shared_n]),
                        jnp.asarray(darr))
            nb = self._nb_bucket(-(-(start + sb) // bs_))
            tables = self._lane_tables(slots, nb)
            self._rng, rkey = jax.random.split(self._rng)
            with self._ctx():
                head, _, self.cache = self._paged_prefill(
                    self.params, jnp.asarray(tokens),
                    jnp.full((width,), start, jnp.int32),
                    jnp.asarray(true_pos), self.cache, tables,
                    jnp.asarray(temps), rkey,
                    jnp.full((width,), aid, jnp.int32), False)
            first_np, first_lp_np, tids, tlps = _unpack_head(
                np.asarray(head), self.cfg.logprob_topk)  # jit-ok: ONE transfer per prefill
            top_np = (tids, tlps)
            now = time.time()
            for i, (req, slot, submit_time, n, _, max_new) in \
                    enumerate(chunk):
                s = _Slot(req, length=n, submit_time=submit_time,
                          max_new=max_new)
                s.first_token_time = now
                s.generated.append(int(first_np[i]))
                s.lps.append(float(first_lp_np[i]))
                s.tops.append(_pairs(top_np[0][i], top_np[1][i]))
                self._slots[slot] = s
                self._lengths[slot] = n
                self._last_tokens[slot] = s.generated[0]
                self._temps[slot] = req.temperature
                self._slot_adapters[slot] = aid
            self.prefix_stats['hits'] += p
            self.prefix_stats['tokens_reused'] += start * p

    def _start_radix_group_paged(self, group, sb: int,  # locked: _lock
                                 gen: int) -> None:
        """Start radix-matched requests sharing a suffix bucket: each
        slot's table gets its matched blocks by REFERENCE (refcount
        bump — matches are block-aligned, so unlike the registered-
        prefix path there is never a tail block to privatize), then
        the suffixes forward in lane-batched paged_prefill dispatches
        with PER-LANE dynamic starts — lanes with different match
        lengths (and adapters) share one dispatch, so the compile key
        stays (sb, table width).

        group: ((req, slot, submit_time, n, bucket, max_new), start,
        blocks) triples.  `gen` is the tree generation the matches
        were taken under; everything from match to refcount bump runs
        under one lock acquisition, so a mismatch means a reset slid
        in between — fail loudly rather than share dead blocks."""
        assert gen == self._radix.generation, (
            'radix tree reset between match and start '
            f'({gen} != {self._radix.generation})')
        bs_ = self.cfg.kv_block_size
        lanes = self.cfg.prefill_lanes
        for ofs in range(0, len(group), lanes):
            chunk = group[ofs:ofs + lanes]
            p = len(chunk)
            width = lanes
            tokens = np.zeros((width, sb), np.int32)
            starts = np.zeros((width,), np.int32)
            true_pos = np.zeros((width,), np.int32)
            slots = np.zeros((width,), np.int32)
            temps = np.zeros((width,), np.float32)
            aids = np.full((width,), -1, np.int32)
            for it, start, blocks in chunk:       # real lanes only
                req, slot, _, n, _, _ = it
                self._append_shared_blocks(slot, blocks)
                self._ensure_blocks(slot, n)
                self.paged_stats['prefix_block_hits'] += len(blocks)
                self.radix_stats['hits'] += 1
                self.radix_stats['tokens_reused'] += start
            for i in range(width):
                it, start, _ = chunk[min(i, p - 1)]
                req, slot, _, n, _, _ = it
                ns = n - start
                tokens[i, :ns] = req.tokens[start:]
                starts[i] = start
                true_pos[i] = ns - 1
                slots[i] = slot
                temps[i] = req.temperature
                aids[i] = self._adapter_id(req)
            assert all(slots[i] == slots[p - 1]
                       for i in range(p, width)), (
                f'pad lanes must duplicate the last real lane: '
                f'{slots=} p={p}')
            # Table width covers every lane's start + suffix bucket
            # (pad lanes duplicate a real lane, so the max is real).
            nb = self._nb_bucket(-(-(int(starts.max()) + sb) // bs_))
            tables = self._lane_tables(slots, nb)
            self._rng, rkey = jax.random.split(self._rng)
            with self._ctx():
                head, _, self.cache = self._paged_prefill(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(starts), jnp.asarray(true_pos),
                    self.cache, tables, jnp.asarray(temps), rkey,
                    jnp.asarray(aids), False)
            first_np, first_lp_np, tids, tlps = _unpack_head(
                np.asarray(head), self.cfg.logprob_topk)  # jit-ok: ONE transfer per prefill
            top_np = (tids, tlps)
            now = time.time()
            for i, (it, start, _) in enumerate(chunk):
                req, slot, submit_time, n, _, max_new = it
                s = _Slot(req, length=n, submit_time=submit_time,
                          max_new=max_new)
                s.first_token_time = now
                s.generated.append(int(first_np[i]))
                s.lps.append(float(first_lp_np[i]))
                s.tops.append(_pairs(top_np[0][i], top_np[1][i]))
                self._slots[slot] = s
                self._lengths[slot] = n
                self._last_tokens[slot] = s.generated[0]
                self._temps[slot] = req.temperature
                self._slot_adapters[slot] = self._adapter_id(req)

    def _start_batch(self, items) -> None:  # locked: _lock
        """Prefill validated requests in batched dispatches.

        Bumps the prefill epoch FIRST: an in-flight lookahead window's
        chain must never be extended across a slot recycle
        (_maybe_dispatch_ahead), and its snapshot keeps recycled slots
        from consuming stale columns — the prefill itself need not
        wait (device execution is one serial stream, so its KV writes
        land after the in-flight window's dead-row writes).

        items: (req, slot, submit_time, prompt_len, bucket, max_new)
        tuples.  Grouped by bucket and chunked to at most prefill_lanes
        rows per dispatch, so a burst of P requests costs ceil(P/lanes)
        dispatches instead of 3*P — the per-dispatch tunnel/driver
        latency dominated prefill cost.  Dispatch width is ALWAYS
        prefill_lanes (exactly one compile per bucket): measured on v5e,
        variable widths recompile per width and a single cold compile
        costs more than thousands of padded-lane forwards, while the
        padding FLOPs are noise next to dispatch latency.  Pad lanes
        duplicate the last real row — rewriting the same slot with the
        same KV rows is idempotent, so no validity masking is needed.
        """
        self._fault_raise('prefill')
        self._prefill_epoch += 1
        if self._radix is not None and items:
            rgroups: Dict[int, list] = {}
            rest = []
            gen = self._radix.generation
            bs_ = self.cfg.kv_block_size
            for it in items:
                req, _, _, n, _, _ = it
                # Prompt scoring needs every prompt position's logits:
                # reused rows have none — same bypass as registered
                # prefixes (requests that skip reuse keep skipping).
                if req.want_prompt_logprobs:
                    rest.append(it)
                    continue
                self.radix_stats['lookups'] += 1
                # Cap the match at n-1 tokens: at least one token must
                # forward to produce the first sampled head, even when
                # the whole prompt is cached.
                blocks = self._radix.match(req.adapter, req.tokens,
                                           n - 1)
                if self._host_tier is not None:
                    # Extend the match from the host tier: restored
                    # blocks dispatch async here and the transfer
                    # hides behind the suffix prefill below.
                    blocks = self._restore_from_tier(req, blocks, n)
                if not blocks:
                    rest.append(it)
                    continue
                start = len(blocks) * bs_
                sb = self._suffix_bucket(start, n - start)
                if sb is None:       # no bucket fits beside the match
                    rest.append(it)
                    continue
                rgroups.setdefault(sb, []).append((it, start, blocks))
            for sb, rgroup in rgroups.items():  # compile-shape: sb=suffix_buckets
                self._start_radix_group_paged(rgroup, sb, gen)
            items = rest
        if self._prefixes:
            groups: Dict[Any, list] = {}
            rest = []
            for it in items:
                # Prompt scoring needs every prompt position's logits:
                # reused prefix rows have none — full prefill.
                m = (None if it[0].want_prompt_logprobs else
                     self._match_prefix(it[0].tokens, it[0].adapter))
                if m is None:
                    rest.append(it)
                    continue
                start, key = m
                sb = self._suffix_bucket(start, len(it[0].tokens) - start)
                if sb is None:       # no bucket fits beside the prefix
                    rest.append(it)
                    continue
                groups.setdefault((key, start, sb), []).append(it)
            # compile-shape: sb=suffix_buckets
            # compile-shape: start=const  (enters jit as shape-() scalar only)
            for (key, start, sb), group in groups.items():
                self._start_prefixed_group(group, start, sb, key)
            items = rest
        if self.cfg.prefill_chunk:
            rest = []
            for it in items:
                req, slot, submit_time, n, bucket, max_new = it
                if not self._should_chunk(req, n, bucket):
                    rest.append(it)
                    continue
                # Reserve the slot without activating it: chunks are
                # written one per serving gap (_chunk_round); decode
                # windows in between write dead rows at the frontier
                # (the length mirror), which later chunks overwrite
                # before any query position reaches them.
                self._chunking[slot] = _ChunkJob(
                    req, slot, submit_time, n, max_new,
                    self._adapter_id(req))
                self._lengths[slot] = 0
                self._temps[slot] = 0.0
                self._slot_adapters[slot] = -1
                self.chunk_stats['requests'] += 1
            items = rest
        lanes = self.cfg.prefill_lanes
        by_bucket: Dict[int, list] = {}
        for it in items:
            by_bucket.setdefault(it[4], []).append(it)
        for bucket, group in by_bucket.items():  # compile-shape: bucket=prefill_buckets
            for ofs in range(0, len(group), lanes):
                chunk = group[ofs:ofs + lanes]
                p = len(chunk)
                width = lanes
                tokens = np.zeros((width, bucket), np.int32)
                true_lens = np.ones((width,), np.int32)
                slots = np.zeros((width,), np.int32)
                temps = np.zeros((width,), np.float32)
                aids = np.full((width,), -1, np.int32)
                for i in range(width):
                    req, slot, _, n, _, _ = chunk[min(i, p - 1)]
                    tokens[i, :n] = req.tokens
                    true_lens[i] = n
                    slots[i] = slot
                    temps[i] = req.temperature
                    aids[i] = self._adapter_id(req)
                # Pad-lane safety invariant (VERDICT r1 weak #6): every
                # pad lane must target the SAME slot as the real lane it
                # duplicates — the fori_loop rewrites that slot's KV
                # rows once per lane, which is only correct because the
                # writes are byte-identical.  A future scheduler change
                # that padded with a DIFFERENT live slot would silently
                # corrupt its cache; fail loudly instead.
                assert all(slots[i] == slots[p - 1]
                           for i in range(p, width)), (
                    f'pad lanes must duplicate the last real lane: '
                    f'{slots=} p={p}')
                want_plp = any(it[0].want_prompt_logprobs
                               for it in chunk)
                self._rng, key = jax.random.split(self._rng)
                if self._paged:
                    for req, slot, _, n, _, _ in chunk:  # real lanes
                        self._ensure_blocks(slot, n)
                    bs_ = self.cfg.kv_block_size
                    tables = self._lane_tables(
                        slots, self._nb_bucket(bucket // bs_))
                    with self._ctx():
                        (head, prompt_packed,
                         self.cache) = self._paged_prefill(
                             self.params, jnp.asarray(tokens),
                             jnp.zeros((width,), jnp.int32),
                             jnp.asarray(true_lens - 1), self.cache,
                             tables, jnp.asarray(temps), key,
                             jnp.asarray(aids), want_plp)
                else:
                    pcache = init_cache(self.model_config, width,
                                        bucket, self.cfg.cache_dtype)
                    with self._ctx():   # mesh+rules active at trace
                        (head, prompt_packed,
                         self.cache) = self._prefill_insert(
                             self.params, jnp.asarray(tokens),
                             jnp.asarray(true_lens), pcache, self.cache,
                             jnp.asarray(slots), jnp.asarray(temps),
                             key, jnp.asarray(aids), want_plp)
                topk = self.cfg.logprob_topk
                first_np, first_lp_np, tids, tlps = _unpack_head(
                    np.asarray(head), topk)  # jit-ok: ONE transfer per prefill
                top_np = (tids, tlps)
                if want_plp:
                    pbuf = np.asarray(prompt_packed)     # [P, S-1, 1+2k]
                    plp_np = pbuf[..., 0]
                    ptop_np = (np.ascontiguousarray(
                                   pbuf[..., 1:1 + topk]).view(np.int32),
                               pbuf[..., 1 + topk:])
                now = time.time()
                for i, (req, slot, submit_time, n, _, max_new) in \
                        enumerate(chunk):
                    s = _Slot(req, length=n, submit_time=submit_time,
                              max_new=max_new)
                    s.first_token_time = now
                    s.generated.append(int(first_np[i]))
                    s.lps.append(float(first_lp_np[i]))
                    s.tops.append(_pairs(top_np[0][i], top_np[1][i]))
                    if req.want_prompt_logprobs:
                        s.prompt_lps = [None] + [
                            float(x) for x in plp_np[i, :n - 1]]
                        s.prompt_tops = [None] + [
                            _pairs(ptop_np[0][i, t], ptop_np[1][i, t])
                            for t in range(n - 1)]
                    self._slots[slot] = s
                    self._lengths[slot] = n
                    self._last_tokens[slot] = s.generated[0]
                    self._temps[slot] = req.temperature
                    self._slot_adapters[slot] = self._adapter_id(req)

    def _chunk_round(self) -> bool:  # locked: _lock
        """Advance EVERY in-progress chunked prefill by one chunk in a
        single full-width dispatch; activate slots whose final chunk
        landed.  Returns True when a dispatch happened (the serving
        loop's `moved`).  Called between decode windows, so an active
        slot's worst-case inter-token stall is one chunk forward
        instead of a whole prefill (TBT <= chunk_ms + window_ms,
        docs/performance.md).

        Two skip guards keep the cache-write invariants intact:

        - an active slot within C of the cache end would get the
          full-width dispatch's C-wide frontier write CLAMPED
          (dynamic_update_slice start > M - C) onto its live rows —
          the same hazard _spec_step guards.  Such slots finish within
          ~C tokens (harvest at length+1 >= M), so skipping this gap
          cannot deadlock: decode keeps running in between.
        - an in-flight lookahead window's dead-row writes land AFTER a
          chunk dispatched now, garbling the chunk's prompt rows at the
          frontier; wait for the next decode step to consume it
          (_maybe_dispatch_ahead is gated off while chunking, so at
          most one window of delay).  With no active slot left to
          consume the pending window, drop it instead — its snapshot
          has no survivors, exactly what _decode_step would do.
        """
        if not self._chunking:
            return False
        self._fault_raise('chunk_round')
        c = self.cfg.prefill_chunk
        m = self.cfg.max_cache_len
        if self._ahead is not None:
            if any(s is not None for s in self._slots):
                return False
            self._ahead = None
        if any(s is not None and s.length + c > m
               for s in self._slots):
            return False
        # An in-flight chain must never be extended across these writes
        # (and a final chunk is a slot recycle, like any prefill).
        self._prefill_epoch += 1
        b = self.cfg.num_slots
        tokens = np.zeros((b, c), np.int32)
        starts = self._lengths.astype(np.int32, copy=True)
        true_pos = np.zeros((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        aids = self._slot_adapters.astype(np.int32, copy=True)
        finals = []
        for slot, job in self._chunking.items():
            real = min(c, job.n - job.done)
            tokens[slot, :real] = job.req.tokens[job.done:job.done + real]
            starts[slot] = job.done
            true_pos[slot] = real - 1
            aids[slot] = job.aid
            if self._paged:
                self._ensure_blocks(slot, job.done + real)
            if job.done + real >= job.n:
                temps[slot] = job.req.temperature
                finals.append((slot, job))
            job.done += real
            # Host mirror tracks the frontier: interleaved decode's
            # dead-row writes for this lane land past the prompt rows
            # already written.
            self._lengths[slot] = job.done
            self.chunk_stats['chunks'] += 1
        self.chunk_stats['rounds'] += 1
        self._rng, key = jax.random.split(self._rng)
        if self._paged:
            # Table width must cover EVERY lane's frontier + C (active
            # lanes write dead rows there); an uncovered position would
            # have its block index clamped into a LIVE block.
            bs_ = self.cfg.kv_block_size
            nb = self._nb_bucket(-(-(int(starts.max()) + c) // bs_))
            tables = self._lane_tables(range(b), nb)
            with self._ctx():
                head, _, self.cache = self._paged_prefill(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(starts), jnp.asarray(true_pos),
                    self.cache, tables, jnp.asarray(temps), key,
                    jnp.asarray(aids), False)
        else:
            with self._ctx():
                head, self.cache = self._chunk_prefill(
                    self.params, jnp.asarray(tokens), jnp.asarray(starts),
                    jnp.asarray(true_pos), self.cache, jnp.asarray(temps),
                    key, jnp.asarray(aids))
        if self._radix is not None:
            # Block-boundary insertion (AFTER the dispatch, so a raised
            # chunk fault never indexes unwritten rows): every full
            # block of prompt rows the pool now holds is matchable
            # immediately — an overlapping prompt arriving mid-prefill
            # reuses them without waiting for this one to finish.
            # finals are still in _chunking here; their completion-time
            # adopt in _finish_slot is an idempotent no-op on top.
            for slot, job in self._chunking.items():
                self._radix_adopt(slot, job.req.tokens, job.done,
                                  job.req.adapter)
        if finals:
            first_np, first_lp_np, tids, tlps = _unpack_head(
                np.asarray(head), self.cfg.logprob_topk)  # jit-ok: ONE transfer per prefill
            now = time.time()
            for slot, job in finals:
                del self._chunking[slot]
                s = _Slot(job.req, length=job.n,
                          submit_time=job.submit_time,
                          max_new=job.max_new)
                s.first_token_time = now
                s.generated.append(int(first_np[slot]))
                s.lps.append(float(first_lp_np[slot]))
                s.tops.append(_pairs(tids[slot], tlps[slot]))
                self._slots[slot] = s
                self._lengths[slot] = job.n
                self._last_tokens[slot] = s.generated[0]
                self._temps[slot] = job.req.temperature
                self._slot_adapters[slot] = job.aid
        return True

    def _radix_adopt(self, slot: int, tokens: Sequence[int],  # locked: _lock
                     rows: int, adapter: Optional[str]) -> None:
        """Insert the slot's full PROMPT blocks (rows [0, rows) of
        `tokens`, whole blocks only) into the radix tree by reference.
        Only prefill-written rows are ever indexed — decode-written
        rows at the same position could differ numerically from a
        fresh prefill (different dispatch shape/accumulation order),
        which would break the radix-on == radix-off byte-identity
        bar.  Idempotent: already-cached runs just get an LRU touch.
        Caller holds the lock."""
        bs_ = self.cfg.kv_block_size
        full = min(rows // bs_, int(self._slot_nblocks[slot]))
        if full < 1:
            return
        blocks = [int(b) for b in self._tables_np[slot, :full]]
        self.radix_stats['inserts'] += self._radix.insert(
            adapter, tokens, blocks, addref=self._addref_block)

    def _flush_streams(self) -> None:
        """Deliver newly generated tokens of every active streaming slot.
        Callback errors are swallowed: a broken consumer must not kill
        the engine loop (its request still finishes normally)."""
        for s in self._slots:
            if s is None or s.request.stream_cb is None:
                continue
            if len(s.generated) > s.streamed:
                chunk = s.generated[s.streamed:]
                s.streamed = len(s.generated)
                try:
                    s.request.stream_cb(list(chunk))
                except Exception:  # noqa: BLE001
                    pass

    def _finish_slot(self, i: int, reason: str,  # locked: _lock
                     error: Optional[str] = None,
                     error_class: Optional[str] = None,
                     ) -> Tuple[Request, RequestResult]:
        s = self._slots[i]
        assert s is not None
        if s.request.stream_cb is not None and \
                len(s.generated) > s.streamed:
            try:
                s.request.stream_cb(list(s.generated[s.streamed:]))
            except Exception:  # noqa: BLE001
                pass
        now = time.time()
        if reason in ('eos', 'length'):
            # Clean finishes feed the QoS shed bound's service-rate
            # EWMA (error/deadline durations would skew it short).
            self._svc_estimator.observe(
                len(s.request.tokens) + len(s.generated),
                now - s.submit_time)
        res = RequestResult(
            request_id=s.request.request_id,
            prompt_tokens=list(s.request.tokens),
            output_tokens=list(s.generated),
            ttft_s=(s.first_token_time or now) - s.submit_time,
            latency_s=now - s.submit_time,
            finish_reason=reason,
            error=error,
            error_class=error_class,
            logprobs=list(s.lps),
            top_logprobs=list(s.tops),
            prompt_logprobs=(list(s.prompt_lps)
                             if s.prompt_lps is not None else None),
            prompt_top_logprobs=(list(s.prompt_tops)
                                 if s.prompt_tops is not None else None))
        req = s.request
        self._slots[i] = None
        self._lengths[i] = 0
        self._temps[i] = 0.0
        self._slot_adapters[i] = -1
        if self._paged:
            if (self._radix is not None and reason != 'error' and
                    not req.want_prompt_logprobs):
                # Adopt the slot's full PROMPT blocks into the radix
                # tree before the table is torn down.  'error' finishes
                # are excluded: a failed dispatch may have left rows
                # unwritten or garbled.
                self._radix_adopt(i, req.tokens, len(req.tokens),
                                  req.adapter)
            self._free_slot_blocks(i)
        if req.request_id is not None:
            self._cancelled.pop(req.request_id, None)   # stale mark
        return req, res

    # ----------------------------------------------------- containment

    def _fail_slot(self, i: int,  # locked: _lock
                   error: str) -> Tuple[Request, RequestResult]:
        """Fail ONE active slot's request with error_class='internal':
        slot + paged blocks freed (_finish_slot owns that discipline),
        partial output returned, already-streamed tokens untouched."""
        self.fault_stats['internal_errors'] += 1
        return self._finish_slot(i, 'error', error=error,
                                 error_class='internal')

    def _fail_chunk_job(self, slot: int, reason: str,  # locked: _lock
                        error: Optional[str] = None,
                        ) -> Tuple[Request, RequestResult]:
        """Terminate a part-prefilled chunk job (reason 'error' or
        'deadline'): release the reserved slot and every block its
        chunks already wrote."""
        job = self._chunking.pop(slot)
        self._lengths[slot] = 0
        self._temps[slot] = 0.0
        self._slot_adapters[slot] = -1
        if self._paged:
            self._free_slot_blocks(slot)
        if error is not None:
            self.fault_stats['internal_errors'] += 1
        if job.req.request_id is not None:
            self._cancelled.pop(job.req.request_id, None)
        now = time.time()
        res = RequestResult(
            request_id=job.req.request_id,
            prompt_tokens=list(job.req.tokens),
            output_tokens=[],
            ttft_s=now - job.submit_time,
            latency_s=now - job.submit_time,
            finish_reason=reason,
            error=error,
            error_class='internal' if error is not None else None)
        return job.req, res

    # ------------------------------------------------------------- qos

    def _park_chunk_job(self, slot: int) -> Request:  # locked: _lock
        """Preempt a part-prefilled prompt at its chunk boundary: the
        reserved slot frees NOW for higher-priority work; the rows
        already written survive as refcounted radix blocks (adopted at
        every chunk boundary — the adopt here is an idempotent catch-up
        for a job parked before its first boundary insert), so resuming
        is a suffix-only prefill, not lost work.  Nothing has streamed
        (a chunk job has no _Slot yet), so the client sees one
        uninterrupted stream whenever the request finally runs.
        Returns the request for the caller to requeue."""
        job = self._chunking.pop(slot)
        if self._radix is not None and job.done > 0:
            self._radix_adopt(slot, job.req.tokens, job.done,
                              job.req.adapter)
        self._lengths[slot] = 0
        self._temps[slot] = 0.0
        self._slot_adapters[slot] = -1
        self._free_slot_blocks(slot)
        self.qos_stats['preemptions'] += 1
        return job.req

    def _maybe_preempt_for(self, exclude) -> Optional[int]:
        """Serving-loop preemption hook: every slot is taken, but an
        INTERACTIVE request is waiting and some BATCH prompt is only
        part-prefilled — park the batch job with the most prefill
        still ahead of it and hand its slot over.  Gated on
        paged + radix (that is what makes park/resume nearly free) and
        on no in-flight lookahead window (its dead-row writes for the
        parked lane would land in blocks the pool has already
        recycled — the same hazard _chunk_round waits out)."""
        if not (self.cfg.qos and self._paged and
                self._radix is not None and self.cfg.prefill_chunk):
            return None
        if self._deferred or not self._sched.waiting('interactive'):
            # Deferred head-of-line work is admitted first regardless
            # of class — preempting for it would be a no-op.
            return None
        victim = None
        with self._lock:
            if self._ahead is not None:
                return None
            remaining = -1
            for slot, job in self._chunking.items():
                if slot in exclude or \
                        qos_mod.classify(job.req) != 'batch':
                    continue
                if job.n - job.done > remaining:
                    victim, remaining = slot, job.n - job.done
            if victim is None:
                return None
            req = self._park_chunk_job(victim)
        self._sched.requeue(req)
        return victim

    def _shed_request(self, req: Request, elapsed: float, reason: str,
                      result_cb) -> None:
        """Typed QoS shed at dequeue — ONE shape for both triggers
        (deadline already expired in queue; projected completion
        cannot meet the deadline).  finish_reason stays 'deadline'
        (the historical eviction shape dashboards and tests pin) and
        the historical deadline_evictions counter still ticks; the
        reason text plus error_class='shed' mark it as an admission
        rejection, and qos/tenant counters record who got shed."""
        with self._lock:
            self.fault_stats['deadline_evictions'] += 1
            self.qos_stats['sheds'] += 1
            self._tenant_row(req.tenant_id)['shed'] += 1
            result_cb(RequestResult(
                request_id=req.request_id,
                prompt_tokens=list(req.tokens),
                output_tokens=[], ttft_s=0.0,
                latency_s=elapsed,
                finish_reason='deadline',
                error=reason, error_class='shed'))

    def _contain_failure(self, exc: BaseException,  # locked: _lock
                         phase: str) -> List[Tuple[Request,
                                                   RequestResult]]:
        """Step-level containment for a decode-phase dispatch failure
        (runs under the engine lock).  Mirrors the prefill containment
        the serve loop has always had, so an exception in _chunk_round/
        _step degrades per-request instead of killing the loop thread.

        Attribution: an InjectedFault names the slot(s) it injured —
        only those requests fail.  Anything unattributed (a REAL device
        error) cannot be bisected post-hoc: decode is one batched
        dispatch with donated cache buffers, so by the time the host
        sees the exception the previous cache may already be invalid.
        The whole active batch is quarantined (failed with
        error_class='internal') and the cache rebuilt (_reset_cache),
        leaving the engine clean for the queue that is still waiting.
        """
        msg = f'{phase} failed: {exc!r}'
        slots_hint = getattr(exc, 'slots', None)
        failed: List[Tuple[Request, RequestResult]] = []
        # The in-flight lookahead window (if any) was dispatched against
        # pre-failure state; drop it rather than consume it.
        self._ahead = None
        if slots_hint:
            for i in slots_hint:
                if 0 <= i < self.cfg.num_slots \
                        and self._slots[i] is not None:
                    failed.append(self._fail_slot(i, msg))
                elif i in self._chunking:
                    failed.append(
                        self._fail_chunk_job(i, 'error', error=msg))
            return failed
        self.fault_stats['quarantined_batches'] += 1
        for i, s in enumerate(self._slots):
            if s is not None:
                failed.append(self._fail_slot(i, msg))
        for slot in list(self._chunking):
            failed.append(self._fail_chunk_job(slot, 'error', error=msg))
        self._reset_cache()
        return failed

    def _select_window(self) -> int:
        """Decode-window policy (adaptive_decode_window): QUEUE-aware,
        not occupancy-based.  TPOT at window K is s + F/K where F is the
        per-dispatch fixed cost and s the marginal per-step cost —
        measured on the tunneled v5e, F ~= 112 ms vs s ~= 16 ms
        (scripts/bench_decode_micro.py), so short windows are only ever
        worth their TPOT tax while an arrival is actually WAITING for
        the next prefill gap with a slot free to take it.  An earlier
        occupancy heuristic (short window whenever few slots are busy)
        gave an interactive user streaming alone the WORST inter-token
        latency — precisely the case a latency profile cares about."""
        steps = self.cfg.decode_steps
        if self.cfg.adaptive_decode_window and (
                # A part-prefilled prompt is a pending arrival: its next
                # chunk rides the gap after this window, so the short
                # window bounds BOTH its time-to-first-token and the
                # active slots' stall the same way a queued arrival does.
                self._chunking or
                (self._arrivals_hint > 0
                 and any(s is None and i not in self._chunking
                         for i, s in enumerate(self._slots)))):
            return min(2, steps)
        return steps

    def _decode_step(self, steps: Optional[int] = None) -> None:  # locked: _lock
        """One decode window for every active slot: consume a pending
        lookahead dispatch if one exists, else dispatch fresh from the
        host mirrors; optionally dispatch the NEXT window from the
        device-resident chain before paying this window's transfer
        (_maybe_dispatch_ahead); then append up to K tokens per slot,
        truncating at EOS / max_new (tokens past a slot's stop point
        are speculative overrun and are discarded — the cache rows
        they wrote are dead and get overwritten when the slot is
        recycled)."""
        if self._ahead is not None:
            packed, chain, snap, epoch = self._ahead
            self._ahead = None
            if epoch != self._prefill_epoch:
                # A prefill happened while this window was in flight:
                # its chain lacks the new slot(s), so no further
                # lookahead hangs off it.  If no snapshot slot is even
                # alive any more, skip the transfer entirely and serve
                # the CURRENT slots a fresh window instead.
                chain = None
                if not any(s is not None and s is snap[i]
                           for i, s in enumerate(self._slots)):
                    packed = None
            if packed is not None:
                if chain is not None:
                    # The pending window (cfg.decode_steps long — ahead
                    # windows are always full) is the in-flight budget.
                    self._maybe_dispatch_ahead(chain, snap,
                                               self.cfg.decode_steps)
                self._consume_window(packed, snap)
                return
        if steps is None:
            steps = self._select_window()
        packed, chain = self._dispatch_decode(steps)
        self._maybe_dispatch_ahead(chain, list(self._slots), steps)
        self._consume_window(packed)

    def _decode_tables(self, horizon: int):
        """Ensure every active slot's blocks for `horizon` more rows
        (capped at its worst-case demand — writes past the cap go to
        the dump block) and build the dispatch table, wide enough to
        cover every lane's frontier + horizon: chunking/idle lanes
        write dead rows there, and an uncovered position's block index
        would be CLAMPED into a live block."""
        for i, s in enumerate(self._slots):
            if s is not None:
                self._ensure_blocks(i, min(
                    int(self._lengths[i]) + horizon,
                    self._slot_cap_rows(len(s.request.tokens),
                                        s.max_new)))
        bs_ = self.cfg.kv_block_size
        nb = self._nb_bucket(
            -(-(int(self._lengths.max()) + horizon) // bs_))
        return self._lane_tables(range(self.cfg.num_slots), nb)

    def _dispatch_decode(self, steps: int):
        """One device dispatch from the HOST slot mirrors.  Returns the
        packed result handle plus the device-resident (tokens, lengths)
        chain for a potential lookahead dispatch."""
        self._rng, key = jax.random.split(self._rng)
        if self._paged:
            tables = self._decode_tables(steps)
            with self._ctx():
                packed, last, lens, self.cache = self._paged_decode(
                    self.params, self.cache,
                    jnp.asarray(self._last_tokens),
                    jnp.asarray(self._lengths), jnp.asarray(self._temps),
                    key, jnp.asarray(self._slot_adapters), tables, steps)
            return packed, (last, lens)
        with self._ctx():           # mesh+rules active at trace time
            packed, last, lens, self.cache = self._decode(
                self.params, self.cache, jnp.asarray(self._last_tokens),
                jnp.asarray(self._lengths), jnp.asarray(self._temps), key,
                jnp.asarray(self._slot_adapters), steps)
        return packed, (last, lens)

    def _maybe_dispatch_ahead(self, chain, snap,  # locked: _lock
                              in_flight_steps: int = 0) -> None:
        """Decode lookahead: dispatch the NEXT full window now, feeding
        the previous dispatch's DEVICE-side final tokens/lengths, so it
        never waits for the current window's host round trip — steady
        state pays max(RTT, compute) per window instead of RTT +
        compute (33 -> 27 ms/token single-stream measured on the
        tunneled v5e).  Safe because decode is per-slot independent:

        - a slot that finishes while the window is in flight has its
          lookahead column discarded (the consume below is restricted
          to `snap`, the slot objects active at dispatch time), and
          its cache writes are the same dead rows windowed decode
          already tolerates;
        - a PREFILL recycling a freed slot does NOT wait: its device
          writes are ordered after the in-flight window's stale writes
          (one serial device stream), the snapshot keeps the new
          request from ever consuming a stale column, and the epoch
          bump keeps further lookahead off the stale chain;
        - while arrivals wait (hint > 0) or a prompt is mid-chunked-
          prefill nothing speculates — the in-flight window would push
          the prefill/chunk back in the device queue (TTFT), and a
          chunk must never be dispatched under an in-flight window's
          frontier writes (_chunk_round waits for _ahead to drain);
        - a window that cannot produce a single deliverable token is
          not dispatched (ADVICE r5): when every surviving snapshot
          slot is guaranteed to finish inside the `in_flight_steps`
          already dispatched but unconsumed (remaining budget - in-
          flight <= 0), the ahead window's tokens would all be
          discarded — pure dispatch waste at every stream tail."""
        if (not self.cfg.decode_lookahead or self.cfg.draft_len > 0 or
                not self._serving or self._arrivals_hint > 0 or
                self._chunking):
            return
        live = [s for i, s in enumerate(snap)
                if s is not None and self._slots[i] is s]
        if not live:
            return          # nobody left to deliver the window to
        if all(min(s.max_new - len(s.generated),
                   self.cfg.max_cache_len - 1 - s.length)
               <= in_flight_steps for s in live):
            return          # every survivor finishes in flight
        self._rng, key = jax.random.split(self._rng)
        # compile-shape: chain=const  (device tokens/lengths, fixed (num_slots,))
        if self._paged:
            # The in-flight window advances device lengths past the
            # host mirror: budget blocks for both windows' rows.
            tables = self._decode_tables(
                in_flight_steps + self.cfg.decode_steps)
            with self._ctx():
                packed, last, lens, self.cache = self._paged_decode(
                    self.params, self.cache, chain[0], chain[1],
                    jnp.asarray(self._temps), key,
                    jnp.asarray(self._slot_adapters), tables,
                    self.cfg.decode_steps)
        else:
            with self._ctx():
                packed, last, lens, self.cache = self._decode(
                    self.params, self.cache, chain[0], chain[1],
                    jnp.asarray(self._temps), key,
                    jnp.asarray(self._slot_adapters),
                    self.cfg.decode_steps)
        self._ahead = ((packed, (last, lens), snap,
                        self._prefill_epoch))

    def _consume_window(self, packed, snap=None) -> None:  # locked: _lock
        # ONE device->host transfer for the whole window (pack_head).
        toks_np, lps_np, gtoks_np, glps_np = _unpack_head(
            np.asarray(packed),  # jit-ok: ONE transfer per window
            self.cfg.logprob_topk)                           # [K, B...]
        sp = self._fault('nonfinite_logits')
        if sp is not None:
            # Poison one lane's logprobs AFTER the transfer: exercises
            # the guard below exactly the way a real NaN blowup in a
            # lane's logits would surface host-side.
            lane = sp.slot
            if lane is None:
                lane = next((i for i, s in enumerate(self._slots)
                             if s is not None), 0)
            lps_np = np.array(lps_np)  # jit-ok: fault-injection path only
            lps_np[:, lane] = np.nan
        bad: List[int] = []
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if snap is not None and snap[i] is not s:
                # The window was dispatched before this slot's current
                # occupant existed: its column belongs to the previous
                # request — never deliver it.
                continue
            for k in range(toks_np.shape[0]):
                if len(s.generated) >= s.max_new:
                    break
                if (self.cfg.eos_id is not None and s.generated and
                        s.generated[-1] == self.cfg.eos_id):
                    break
                if s.length + 1 >= self.cfg.max_cache_len:
                    break
                if not np.isfinite(lps_np[k, i]):
                    # Non-finite logprob: THIS lane's logits blew up
                    # (overflow/degenerate adapter/bad weights slice).
                    # Its remaining window tokens are garbage; kill the
                    # lane, not the batch — other lanes' columns are
                    # independent.  Stop before counting the row so the
                    # cache write stays a dead row.
                    bad.append(i)
                    break
                s.length += 1        # the token we just fed is now cached
                tok = int(toks_np[k, i])
                s.generated.append(tok)
                s.lps.append(float(lps_np[k, i]))
                s.tops.append(_pairs(gtoks_np[k, i], glps_np[k, i]))
            self._lengths[i] = s.length
            if s.generated:
                self._last_tokens[i] = s.generated[-1]
        for i in bad:
            self.fault_stats['nonfinite_lanes'] += 1
            self._pending_failures.append(self._fail_slot(
                i, 'non-finite logits in decode window (lane killed)'))

    def _spec_step(self) -> None:  # locked: _lock
        """One speculative-decode dispatch: draft with prompt-lookup,
        verify [B, 1+D] in one forward, accept the agreed prefix plus
        the model's own next token (so even zero acceptance yields one
        token — exact greedy equivalence with plain decode)."""
        k = self.cfg.draft_len + 1
        cache_len = self.cfg.max_cache_len
        # A slot within k of the cache end would get its k-row cache
        # write CLAMPED by dynamic_update_slice (start > M-k), silently
        # rewriting earlier, still-live rows.  Those slots finish within
        # a few tokens anyway: run exact windowed decode until they do.
        # A chunking slot's frontier is the same hazard (its prompt rows
        # below the frontier are live).
        if (any(s is not None and s.length > cache_len - k
                for s in self._slots) or
                any(job.done > cache_len - k
                    for job in self._chunking.values())):
            self._decode_step()
            return
        b = self.cfg.num_slots
        tokens = np.zeros((b, k), np.int32)
        tokens[:, 0] = self._last_tokens
        drafted = np.zeros((b,), np.int32)
        for i, s in enumerate(self._slots):
            if s is None or s.request.temperature > 0:
                # Sampled slots can't accept greedy-verified drafts
                # (that would need rejection sampling); they ride the
                # dispatch at 1 token each.
                continue
            # A dispatch can append at most `budget` tokens (max_new /
            # cache-boundary), of which the first needs no draft: don't
            # draft past it — wasted lookup work that can never be
            # accepted, and it would understate the reported
            # acceptance rate.
            budget = min(s.max_new - len(s.generated),
                         cache_len - 1 - s.length)
            want = min(self.cfg.draft_len, budget - 1)
            if want < 1:
                continue
            hist = s.request.tokens + s.generated
            drafts = prompt_lookup_draft(hist, want, self.cfg.ngram_max)
            tokens[i, 1:1 + len(drafts)] = drafts
            drafted[i] = len(drafts)
        if not drafted.any():
            # Nothing to verify (all-sampled batch, no n-gram matches,
            # or every slot about to finish): the windowed decode's
            # decode_steps tokens/dispatch beat a 1-token verify.
            self._decode_step()
            return
        active = sum(s is not None for s in self._slots)
        if self._accept_ema * float(drafted.sum()) < 0.5 * active:
            # Expected bonus below half a token per active slot: the
            # whole batch would decode 1 token this dispatch for a few
            # (probably wrong) drafts.  Windowed decode, with a rare
            # verify probe to keep the EMA live as traffic shifts.
            self._spec_skips += 1
            if self._spec_skips < 50:
                self._decode_step()
                return
        self._spec_skips = 0
        self._rng, key = jax.random.split(self._rng)
        if self._paged:
            tables = self._decode_tables(k)
            with self._ctx():
                packed, self.cache = self._paged_spec_verify(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(self._lengths), jnp.asarray(self._temps),
                    key, jnp.asarray(self._slot_adapters), tables)
        else:
            with self._ctx():
                packed, self.cache = self._spec_verify(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(self._lengths), jnp.asarray(self._temps),
                    key, jnp.asarray(self._slot_adapters))
        preds_np, preds_lp_np, g_toks_np, g_lps_np = _unpack_head(
            np.asarray(packed),  # jit-ok: ONE transfer per verify
            self.cfg.logprob_topk)                           # [B, K...]
        self.spec_stats['dispatches'] += 1
        accepted_before = self.spec_stats['accepted']
        bad: List[int] = []
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            self.spec_stats['drafted'] += int(drafted[i])
            for t in range(k):
                if len(s.generated) >= s.max_new:
                    break
                if (self.cfg.eos_id is not None and s.generated and
                        s.generated[-1] == self.cfg.eos_id):
                    break
                if s.length + 1 >= cache_len:
                    break
                if not np.isfinite(preds_lp_np[i, t]):
                    # Same per-lane guard as _consume_window: a blown-up
                    # lane dies alone.
                    bad.append(i)
                    break
                if t > 0:
                    # Position t fed draft tokens[i, t]; it only counts
                    # if the model's prediction at t-1 agrees (and only
                    # for greedy slots — sampled ones verified nothing).
                    if (s.request.temperature > 0 or t > drafted[i] or
                            int(tokens[i, t]) != int(preds_np[i, t - 1])):
                        break
                    self.spec_stats['accepted'] += 1
                s.length += 1
                s.generated.append(int(preds_np[i, t]))
                s.lps.append(float(preds_lp_np[i, t]))
                s.tops.append(_pairs(g_toks_np[i, t], g_lps_np[i, t]))
            self._lengths[i] = s.length
            self._last_tokens[i] = s.generated[-1]
        for i in bad:
            self.fault_stats['nonfinite_lanes'] += 1
            self._pending_failures.append(self._fail_slot(
                i, 'non-finite logits in spec verify (lane killed)'))
        dispatch_drafted = int(drafted.sum())
        dispatch_accepted = (self.spec_stats['accepted'] -
                             accepted_before)
        if dispatch_drafted:
            rate = dispatch_accepted / dispatch_drafted
            self._accept_ema = 0.9 * self._accept_ema + 0.1 * rate

    _CANCEL_MARK_TTL_S = 600.0

    def cancel(self, request_id: str) -> bool:
        """Stop generating for an in-flight request and free its slot
        NOW (client disconnected mid-stream / server-side stop-string
        hit): without this, an abandoned request burns its decode slot
        to max_new_tokens.  A still-pending request is dropped at
        dequeue time instead.  Returns True when the id was found
        in a slot (its RequestResult is NOT delivered — the caller
        initiated the cancel and owns the consequence); False marks it
        for pending-drop."""
        with self._lock:
            self._prune_cancel_marks()
            for i, s in enumerate(self._slots):
                if s is not None and s.request.request_id == request_id:
                    self._finish_slot(i, 'cancelled')
                    return True
            for slot, job in list(self._chunking.items()):
                if job.req.request_id == request_id:
                    # Mid-chunked-prefill: free the reserved slot; the
                    # partially written prompt rows are dead (the next
                    # occupant's prefill/decode overwrites every row
                    # before reading it).
                    del self._chunking[slot]
                    self._lengths[slot] = 0
                    if self._paged:
                        self._free_slot_blocks(slot)
                    return True
            self._cancelled[request_id] = time.time()
            return False

    def uncancel(self, request_id: str) -> None:
        """Drop a pending-cancel mark.  For the caller who learns —
        after cancel() returned False — that the request had already
        finished naturally (cancel raced the finish): without this the
        stale mark silently drops a retry reusing the same
        client-supplied request_id for up to _CANCEL_MARK_TTL_S."""
        with self._lock:
            self._cancelled.pop(request_id, None)

    def _prune_cancel_marks(self) -> None:  # locked: _lock
        now = time.time()
        stale = [rid for rid, ts in self._cancelled.items()
                 if now - ts > self._CANCEL_MARK_TTL_S]
        for rid in stale:
            del self._cancelled[rid]

    def _step(self) -> None:
        """One decode dispatch: speculative verify when drafting is
        enabled, else the windowed (lax.scan) decode."""
        self._fault_raise('decode_step')
        if self.cfg.draft_len > 0:
            self._spec_step()
        else:
            self._decode_step()

    def _harvest(self) -> List[Tuple[Request, RequestResult]]:  # locked: _lock
        done = []
        if self._pending_failures:
            # Lanes killed inside the dispatch path (non-finite guard):
            # deliver through the same channel as every other finish.
            done.extend(self._pending_failures)
            self._pending_failures = []
        now = time.time()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            dl = s.request.deadline_s
            if dl is not None and now - s.submit_time >= dl:
                # Deadline eviction: the client stopped caring; partial
                # output ships, the slot and its paged blocks free NOW
                # instead of at max_new.  Checked before eos/length so
                # an expired request never counts as a clean finish.
                self.fault_stats['deadline_evictions'] += 1
                done.append(self._finish_slot(i, 'deadline'))
            elif self.cfg.eos_id is not None and \
                    s.generated[-1] == self.cfg.eos_id:
                done.append(self._finish_slot(i, 'eos'))
            elif len(s.generated) >= s.max_new:
                done.append(self._finish_slot(i, 'length'))
            elif s.length + 1 >= self.cfg.max_cache_len:
                done.append(self._finish_slot(i, 'length'))
        for slot, job in list(self._chunking.items()):
            dl = job.req.deadline_s
            if dl is not None and now - job.submit_time >= dl:
                # A part-prefilled prompt past its deadline: stop paying
                # chunk dispatches for a result nobody will read.
                self.fault_stats['deadline_evictions'] += 1
                done.append(self._fail_chunk_job(slot, 'deadline'))
        return done

    # -------------------------------------------------------------- API

    def generate(self, requests: List[Request]) -> List[RequestResult]:
        """Offline batch generation with continuous batching: slots are
        refilled from the pending list as requests finish.  Runs full
        decode windows (no backlog exists here) — warmup_decode sets
        the hint deliberately to compile the short variant."""
        with self._lock:
            pending = list(requests)
            finished: List[Tuple[Request, RequestResult]] = []
            t0 = time.time()
            while (pending or self._chunking or
                   any(s is not None for s in self._slots)):
                # Offline batch: fill ALL free slots before decoding —
                # total throughput wants the widest decode batch, and
                # measured on v5e, capping prefills here costs ~20%
                # tok/s without helping batch-start TTFT.  (The serving
                # loop generate_stream DOES cap, to protect in-flight
                # requests' latency during bursts.)
                to_start = []
                admit_extra = 0
                while pending:
                    slot = self._free_slot(exclude=[it[1]
                                                    for it in to_start])
                    if slot is None:
                        break
                    if self._paged:
                        req = pending[0]
                        demand = self._blocks_demand(
                            len(req.tokens), self._max_new(req))
                        # Oversized demand falls through to
                        # _validate_request, which fails the request
                        # alone instead of deferring it forever.
                        if demand <= self._num_blocks - 1 and \
                                not self._can_admit_blocks(demand,
                                                           admit_extra):
                            # Nothing running and nothing about to:
                            # evict prefix entries rather than deadlock
                            # (validation bounds demand by the pool).
                            if (to_start or self._chunking or
                                    any(s is not None
                                        for s in self._slots) or
                                    not self._force_admit_blocks(
                                        demand)):
                                self.paged_stats['deferred'] += 1
                                break
                    req = pending.pop(0)
                    try:
                        to_start.append((req, slot, t0,
                                         *self._validate_request(req)))
                        if self._paged:
                            admit_extra += demand
                    except ValueError as e:
                        # A bad request fails alone, not the whole batch.
                        finished.append((req, RequestResult(
                            request_id=req.request_id,
                            prompt_tokens=list(req.tokens),
                            output_tokens=[], ttft_s=0.0, latency_s=0.0,
                            finish_reason='error', error=str(e),
                            error_class='client')))
                if to_start:
                    self._start_batch(to_start)
                if self._chunking:
                    # Offline, only prompts no bucket can hold chunk
                    # (_should_chunk): one chunk per loop iteration,
                    # interleaved with the decode windows below.
                    try:
                        self._chunk_round()
                    except Exception as e:  # pylint: disable=broad-except
                        finished.extend(
                            self._contain_failure(e, 'chunk round'))
                # Harvest between prefill and decode: the prefill already
                # produced one token, which may satisfy max_new_tokens=1
                # or be the EOS.
                finished.extend(self._harvest())
                if not any(s is not None for s in self._slots):
                    continue
                try:
                    self._step()
                except Exception as e:  # pylint: disable=broad-except
                    # Same containment as the serving loop: a decode
                    # failure costs the affected (or, unattributed, the
                    # active) requests — the rest of the batch and the
                    # still-pending list keep going.
                    finished.extend(
                        self._contain_failure(e, 'decode step'))
                finished.extend(self._harvest())
            order = {id(r): i for i, r in enumerate(requests)}
            finished.sort(key=lambda pair: order.get(id(pair[0]), 0))
            return [res for _, res in finished]

    # Consecutive fast deaths (loop up < 1 s) before the supervisor
    # gives up: a crash loop that never makes progress must surface to
    # the caller instead of spinning and silently eating the queue.
    _MAX_LOOP_RESTARTS = 3

    def generate_stream(self, request_queue: 'queue.Queue[Request]',
                        result_cb, stop_event: threading.Event,
                        idle_sleep: float = 0.005) -> None:
        """Server loop: pull requests from a queue, run continuous
        batching forever, deliver RequestResults via result_cb.

        Supervised: the loop thread is the whole data plane, so an
        exception that escapes _serve_loop's contained regions must not
        strand its clients.  The supervisor (1) fails every in-flight
        request NOW with error_class='internal' — clients hear within
        one loop pass, not when their own timeouts trip; (2) restarts
        the loop with the queue intact, so requests behind the failure
        still serve; (3) gives up after _MAX_LOOP_RESTARTS consecutive
        sub-second deaths (a crash loop making no progress), failing
        the queued requests too and re-raising to the caller.
        """
        consecutive = 0
        try:
            self._serving = True
            while True:
                t_up = time.time()
                try:
                    self._serve_loop(request_queue, result_cb,
                                     stop_event, idle_sleep)
                    return
                except Exception as e:  # pylint: disable=broad-except
                    with self._lock:
                        self.fault_stats['loop_restarts'] += 1
                        self._ahead = None
                        for _, res in self._fail_all_inflight(
                                f'serving loop died: {e!r}'):
                            try:
                                result_cb(res)
                            except Exception:  # noqa: BLE001
                                pass
                    if stop_event.is_set():
                        return
                    consecutive = (consecutive + 1
                                   if time.time() - t_up < 1.0 else 1)
                    if consecutive > self._MAX_LOOP_RESTARTS:
                        self._drain_queue_failing(request_queue,
                                                  result_cb, e)
                        raise
        finally:
            # A loop stopped with a non-empty queue must not leave a
            # stale positive hint that would force short windows on
            # later offline generate() calls (the init invariant:
            # hint is 0 outside the serving loop).  A pending lookahead
            # dies with the loop (its requests are abandoned anyway).
            self._serving = False
            with self._lock:
                self._ahead = None
                self._arrivals_hint = 0

    def _fail_all_inflight(self, msg: str) -> List[Tuple[Request,
                                                         RequestResult]]:
        """Fail every active slot and chunk job with
        error_class='internal' (caller holds the lock).  Used by the
        supervisor: by the time the loop thread is dead, nothing will
        ever advance these requests again."""
        failed: List[Tuple[Request, RequestResult]] = []
        for i, s in enumerate(self._slots):
            if s is not None:
                failed.append(self._fail_slot(i, msg))
        for slot in list(self._chunking):
            failed.append(self._fail_chunk_job(slot, 'error', error=msg))
        return failed

    def _drain_queue_failing(self, request_queue, result_cb,
                             exc: BaseException) -> None:
        """Terminal supervisor path: the loop is crash-looping, so the
        queued (and admission-deferred) requests will never serve —
        fail them all now rather than leave their clients blocking on
        timeouts."""
        with self._lock:
            pending = list(self._deferred)
            self._deferred = []
            while True:       # scheduler backlog dies with the loop too
                r = self._sched.pop()
                if r is None:
                    break
                pending.append(r)
            while True:
                try:
                    pending.append(request_queue.get_nowait())
                except queue.Empty:
                    break
            for req in pending:
                self.fault_stats['internal_errors'] += 1
                try:
                    result_cb(RequestResult(
                        request_id=req.request_id,
                        prompt_tokens=list(req.tokens),
                        output_tokens=[], ttft_s=0.0, latency_s=0.0,
                        finish_reason='error',
                        error=f'serving loop dead: {exc!r}',
                        error_class='internal'))
                except Exception:  # noqa: BLE001
                    pass

    def _serve_loop(self, request_queue, result_cb, stop_event,
                    idle_sleep) -> None:
        while not stop_event.is_set():
            if self._faults is not None:
                # Injection sites for the chaos tests (guarded so the
                # unarmed loop pays one attribute check per pass).
                sp = self._fault('stall')
                if sp is not None:
                    time.sleep(sp.stall_s)   # wedged host thread
                if self._chunking or any(s is not None
                                         for s in self._slots):
                    # Loop death OUTSIDE every contained region — the
                    # supervisor's case.  Consulted only on passes with
                    # work in flight so a plan's "hit 1" is
                    # deterministic w.r.t. request state instead of
                    # racing the idle spin.
                    self._fault_raise('serve_loop')
            moved = False
            to_start = []
            admit_extra = 0
            dequeued = cancelled_deq = 0
            # Drain arrivals into the scheduler seam: admission ORDER
            # is the scheduler's call (FIFO by default, priority +
            # per-tenant WFQ under cfg.qos — infer/scheduler.py), not
            # this loop's.
            while True:
                try:
                    self._sched.push(request_queue.get_nowait())
                except queue.Empty:
                    break
            while True:
                if len(to_start) >= self.cfg.prefills_per_gap and any(
                        s is not None for s in self._slots):
                    break  # let active slots decode; prefill more next gap
                excl = [it[1] for it in to_start]
                slot = self._free_slot(exclude=excl)
                if slot is None:
                    # QoS preemption: an interactive arrival may take
                    # over a part-prefilled batch prompt's slot at its
                    # chunk boundary (no-op unless cfg.qos).
                    slot = self._maybe_preempt_for(excl)
                    if slot is None:
                        break
                # Admission-deferred requests go first (head-of-line:
                # a big request must not starve behind a stream of
                # small ones that keep fitting around it).
                from_deferred = bool(self._deferred)
                if from_deferred:
                    req = self._deferred.pop(0)
                else:
                    req = self._sched.pop()
                    if req is None:
                        break
                if self._paged:
                    demand = self._blocks_demand(
                        len(req.tokens), self._max_new(req))
                    # Under the lock: _can_admit_blocks may now EVICT
                    # radix leaves (a pool mutation), and the lock is
                    # also what serializes this check against a
                    # quarantine _reset_cache — a deferred request
                    # replayed here never sees a half-cleared tree.
                    with self._lock:
                        admissible = (demand > self._num_blocks - 1 or
                                      self._can_admit_blocks(demand,
                                                             admit_extra))
                    if not admissible and not to_start and \
                            not self._chunking and \
                            not any(s is not None for s in self._slots):
                        with self._lock:    # mutates self._prefixes
                            admissible = self._force_admit_blocks(demand)
                    if not admissible:
                        # Put it back at the head and stop dequeuing:
                        # it is admitted first once blocks free up.
                        with self._lock:
                            self._deferred.insert(0, req)
                            self.paged_stats['deferred'] += 1
                        break
                if (req.request_id is not None and
                        req.request_id in self._cancelled):
                    # Cancelled while queued: never prefill it.
                    self._cancelled.pop(req.request_id, None)
                    # Terminal results are delivered UNDER the lock —
                    # here and at every other result_cb site in this
                    # loop.  cancel() also takes the lock, so a caller
                    # who sees cancel() return False can rely on any
                    # prior finish's sentinel being already enqueued
                    # (submit_stream's stale-mark re-drain needs this
                    # on the error/cancel paths, not just harvest).
                    with self._lock:
                        result_cb(RequestResult(
                            request_id=req.request_id,
                            prompt_tokens=list(req.tokens),
                            output_tokens=[], ttft_s=0.0, latency_s=0.0,
                            finish_reason='cancelled'))
                    moved = True
                    dequeued += 1
                    cancelled_deq += 1
                    continue
                dequeued += 1
                now = time.time()
                elapsed = (now - req.arrival_time
                           if req.arrival_time is not None else 0.0)
                shed_reason = None
                projection_miss = False
                if (req.deadline_s is not None and
                        req.arrival_time is not None and
                        elapsed >= req.deadline_s):
                    # Expired while queued: never spend a prefill on
                    # it.  (Without arrival_time the deadline clock
                    # starts at the submit_time below; _harvest
                    # enforces it.)
                    shed_reason = (
                        f'deadline_s={req.deadline_s} expired in '
                        f'queue ({elapsed:.3f}s elapsed)')
                elif self.cfg.qos and req.deadline_s is not None:
                    # Projection bound: with the observed service rate,
                    # could this request's prefill + decode still land
                    # inside its deadline?  If not, reject NOW — a
                    # typed shed the client can retry elsewhere beats a
                    # guaranteed mid-flight deadline eviction later.
                    proj = self._svc_estimator.projected_s(
                        len(req.tokens) + self._max_new(req))
                    if proj is not None and \
                            elapsed + proj > req.deadline_s:
                        shed_reason = (
                            f'projected completion {elapsed + proj:.3f}s '
                            f'cannot meet deadline_s={req.deadline_s}')
                        projection_miss = True
                if shed_reason is not None and projection_miss and \
                        hasattr(self._sched, 'shed_victim'):
                    # WFQ-aware shed order (parked since PR 8): before
                    # sacrificing the popped head — which WFQ sorts to
                    # the UNDER-share tenant — shed a doomed queued row
                    # from a tenant strictly more over its fair share.
                    # Only rows that would miss their own deadlines
                    # qualify (the `doomed` bound), so totals are
                    # unchanged; fairness just picks who goes first.
                    def _doomed(r, _now=now):
                        if r.deadline_s is None:
                            return False
                        el = (_now - r.arrival_time
                              if r.arrival_time is not None else 0.0)
                        p = self._svc_estimator.projected_s(
                            len(r.tokens) + self._max_new(r))
                        return p is not None and el + p > r.deadline_s
                    victim = self._sched.shed_victim(
                        prefer_over=req.tenant_id or
                        qos_mod.DEFAULT_TENANT,
                        doomed=_doomed)
                    if victim is not None:
                        self._sched.requeue(req)
                        v_el = (now - victim.arrival_time
                                if victim.arrival_time is not None
                                else 0.0)
                        self._shed_request(
                            victim, v_el,
                            'over-fair-share victim: projected '
                            'completion cannot meet '
                            f'deadline_s={victim.deadline_s}',
                            result_cb)
                        moved = True
                        continue
                if shed_reason is not None:
                    self._shed_request(req, elapsed, shed_reason,
                                       result_cb)
                    moved = True
                    continue
                try:
                    to_start.append((req, slot,
                                     req.arrival_time or time.time(),
                                     *self._validate_request(req)))
                    if self._paged:
                        admit_extra += demand
                except ValueError as e:
                    with self._lock:
                        result_cb(RequestResult(
                            request_id=req.request_id,
                            prompt_tokens=list(req.tokens),
                            output_tokens=[],
                            ttft_s=0.0, latency_s=0.0,
                            finish_reason='error',
                            error=str(e), error_class='client'))
                moved = True
            if dequeued:
                # Phantom-arrival decay (ADVICE r5): a burst of
                # cancelled-while-queued requests leaves qsize() high
                # for a while even though nothing will ever prefill —
                # without decay that forces 2-step windows and disables
                # lookahead.  Each consecutive cancel-only drain halves
                # the hint's view of the backlog; any real dequeue
                # (including validation errors, which DID occupy the
                # queue legitimately) resets it.
                if dequeued == cancelled_deq:
                    self._cancel_only_streak = min(
                        self._cancel_only_streak + 1, 16)
                else:
                    self._cancel_only_streak = 0
            if to_start:
                try:
                    with self._lock:
                        # Re-check cancel marks UNDER the lock: a
                        # cancel() racing the (unlocked) dequeue above
                        # sees the request neither queued nor slotted
                        # and leaves only a pending mark — honoring it
                        # here closes the window where a cancelled
                        # request would still prefill and decode.
                        dropped = [
                            it for it in to_start
                            if it[0].request_id is not None and
                            it[0].request_id in self._cancelled
                        ]
                        to_start = [it for it in to_start
                                    if it not in dropped]
                        for it in dropped:
                            self._cancelled.pop(it[0].request_id, None)
                        if to_start:
                            self._start_batch(to_start)
                            for it in to_start:
                                self._tenant_row(
                                    it[0].tenant_id)['admitted'] += 1
                        for it in dropped:
                            result_cb(RequestResult(
                                request_id=it[0].request_id,
                                prompt_tokens=list(it[0].tokens),
                                output_tokens=[], ttft_s=0.0,
                                latency_s=0.0, finish_reason='cancelled'))
                except Exception as e:  # pylint: disable=broad-except
                    # ANY failure must not kill the serving loop (the
                    # thread is the whole data plane); report every
                    # request of the batch as an internal error and free
                    # any slot a partially-applied batch already filled
                    # (otherwise it would ALSO produce a harvest result).
                    # Slot-state mutation happens under the lock, like
                    # every other mutation.
                    with self._lock:
                        for req, slot, *_ in to_start:
                            s = self._slots[slot]
                            if s is not None and s.request is req:
                                self._slots[slot] = None
                                self._lengths[slot] = 0
                                self._temps[slot] = 0.0
                            if self._paged and self._slots[slot] is None \
                                    and slot not in self._chunking:
                                # Blocks a half-applied batch already
                                # allocated for this slot would leak.
                                self._free_slot_blocks(slot)
                        for req, slot, *_ in to_start:
                            self.fault_stats['internal_errors'] += 1
                            result_cb(RequestResult(
                                request_id=req.request_id,
                                prompt_tokens=list(req.tokens),
                                output_tokens=[], ttft_s=0.0,
                                latency_s=0.0,
                                finish_reason='error', error=str(e),
                                error_class='internal'))
            with self._lock:
                if self._chunking:
                    # At most ONE chunk between decode windows: the
                    # stall any active slot sees from a long-prompt
                    # arrival is bounded by chunk_ms + window_ms
                    # instead of the full prefill duration.
                    # Contained like prefill: a chunk-dispatch failure
                    # costs the attributed (or all chunking/active)
                    # requests, never the loop.
                    try:
                        moved = self._chunk_round() or moved
                    except Exception as e:  # pylint: disable=broad-except
                        for _, res in self._contain_failure(
                                e, 'chunk round'):
                            result_cb(res)
                        moved = True
                self._flush_streams()            # prefill first tokens
                for _, res in self._harvest():   # prefill-only finishes
                    result_cb(res)
                if any(s is not None for s in self._slots):
                    # Snapshot the backlog for the window policy: only
                    # requests still queued at step time are waiting on
                    # the next prefill gap (the cap/slot-exhaustion
                    # leftovers from the dequeue phase above).  A
                    # cancel-only streak decays the hint (see above).
                    self._arrivals_hint = (
                        (request_queue.qsize() + self._sched.backlog())
                        >> self._cancel_only_streak)
                    # The decode phase gets the same step-level
                    # containment prefill has always had: fail the
                    # injured requests, quarantine what can't be
                    # attributed, keep serving (_contain_failure).
                    try:
                        self._step()
                    except Exception as e:  # pylint: disable=broad-except
                        for _, res in self._contain_failure(
                                e, 'decode step'):
                            result_cb(res)
                    self._flush_streams()
                    for _, res in self._harvest():
                        result_cb(res)
                    moved = True
            if not moved:
                if self._host_tier is not None:
                    # Land in-flight spill copies while idle so the
                    # next restore probe never pays the gather.
                    with self._lock:
                        self._host_tier.finalize()
                # Quiesce point: nothing in flight moved this pass, so
                # the block pool's refcounts must balance exactly,
                # every jit root's compile count must sit within its
                # provable bound, and the live root inputs must hold
                # their declared shardings (each no-op unless its
                # sanitizer gate / SKYTPU_SANITIZERS is on).
                sanitizers.maybe_check_block_conservation(self)
                sanitizers.maybe_check_compile_budget(self)
                sanitizers.maybe_check_shard_layout(self)
                time.sleep(idle_sleep)

    def warmup(self) -> Dict[str, int]:
        """Deterministic warmup-on-boot: compile the root x bucket
        shapes the skycheck COMPILE pass enumerates — one monolithic
        prefill per configured bucket, both decode-window variants,
        the chunk kernel, the radix suffix path, and the speculative
        verify — so a fresh scale-up replica serves its FIRST request
        at steady-state TTFT instead of paying compiles in-band.
        Runs through offline generate(): call it BEFORE
        generate_stream starts (infer/server.py does, gated by
        --warmup / SKYTPU_SERVE_WARMUP; both bench suites call it in
        place of their old hand-warm loops)."""
        dispatches = 0
        buckets = list(self.cfg.prefill_buckets)
        for bi, bkt in enumerate(buckets):
            # Length == bucket lands exactly in that bucket; distinct
            # token values keep later prompts off the radix fast path
            # (each bucket must compile the MONOLITHIC prefill).
            n = min(bkt, self.cfg.max_cache_len - 1)
            self.generate([Request(tokens=[bi + 2] * n,
                                   max_new_tokens=2)])
            dispatches += 1
        # Decode-window variants + the chunk kernel.
        self.warmup_decode([1, 2, 3])
        dispatches += 1
        # Radix suffix path: anchor one cached block, then re-issue it
        # with a suffix sized to land in EACH bucket, so the radix-hit
        # prefill (dynamic start, suffix bucket) compiles for every
        # suffix shape the COMPILE pass enumerates — not just the
        # smallest one.
        if self._radix is not None and buckets:
            bs_ = self.cfg.kv_block_size
            base = [2] * bs_
            if bs_ + 3 <= self.cfg.max_cache_len:
                self.generate([Request(tokens=base + [3],
                                       max_new_tokens=2)])
                dispatches += 1
                for si, sb in enumerate(buckets):
                    if bs_ + sb + 2 > self.cfg.max_cache_len:
                        continue
                    # Distinct suffix values per bucket keep the match
                    # pinned at the one shared base block.
                    sfx = base + [si + 4] * sb
                    self.generate([Request(tokens=sfx,
                                           max_new_tokens=2)])
                    dispatches += 1
        self._warm_spec(min(max(buckets[0] if buckets else 8, 8), 64))
        return {'prefill_buckets': len(buckets),
                'warmup_requests': dispatches}

    def warmup_decode(self, tokens: Sequence[int]) -> None:
        """Compile every decode-window variant outside the serving /
        measurement path: a plain warmup request compiles only the FULL
        decode_steps window (the queue-aware policy runs full windows
        whenever nothing is waiting) — the short variant would then jit
        mid-serving on the first real burst, stalling the whole data
        plane for the compile.  num_slots == 1 skips it: the short
        window requires a free slot while another decodes, unreachable
        with one slot (in serving too, so no compile is needed)."""
        self.generate([Request(tokens=list(tokens), max_new_tokens=2)])
        if (self.cfg.adaptive_decode_window and self.cfg.decode_steps > 2
                and self.cfg.num_slots >= 2):
            self._arrivals_hint = 1  # lock-ok: warmup, pre-serving
            try:
                self.generate([Request(tokens=list(tokens),
                                       max_new_tokens=2)])
            finally:
                self._arrivals_hint = 0  # lock-ok: warmup, pre-serving
        if self.cfg.prefill_chunk:
            # Compile the chunk kernel too: one [B, C] dispatch shape
            # covers every chunk round, so a single over-bucket warmup
            # prompt (bucket=None -> _should_chunk) compiles it.
            n = min(max(self.cfg.prefill_buckets) + 1,
                    self.cfg.max_cache_len - 1)
            base = list(tokens) or [1]
            rep = (base * (n // len(base) + 1))[:n]
            self.generate([Request(tokens=rep, max_new_tokens=1)])

    def _warm_spec(self, prompt_len: int) -> None:
        """Compile the speculative verify path outside a benchmark's
        measurement window: a repetitive prompt guarantees drafts, so
        _spec_step actually dispatches (a random warmup prompt rarely
        drafts and would leave the compile inside the timed run)."""
        if not self.cfg.draft_len:
            return
        ema = self._accept_ema
        stats = dict(self.spec_stats)
        rep = ([7, 8] * (prompt_len // 2 + 1))[:max(prompt_len, 4)]
        self.generate([Request(tokens=rep, max_new_tokens=4)])
        self._accept_ema = ema  # lock-ok: warmup must not bias policy
        self.spec_stats.update(stats)

    def benchmark_serving(self, num_requests: int = 64,
                          prompt_len: int = 219, new_tokens: int = 188,
                          qps: Optional[float] = None,
                          seed: int = 0) -> Dict[str, float]:
        """SERVING benchmark: requests arrive over time (Poisson at
        `qps`; None = all at once) into the continuous-batching server
        loop — TTFT here is a real time-to-first-token under load, not
        offline-batch queueing.  Reports the JetStream-comparable rows
        (req/s, tok/s, TTFT p50/p99, TPOT p50/p99; reference anchor:
        examples/tpu/v6e/README.md:114-127)."""
        rng = np.random.default_rng(seed)
        reqs = [
            Request(tokens=rng.integers(
                0, self.model_config.vocab_size,
                size=prompt_len).tolist(),
                    max_new_tokens=new_tokens, request_id=str(i))
            for i in range(num_requests)
        ]
        # Compile both phases (and both window variants) outside the
        # measurement.
        self.warmup_decode(reqs[0].tokens)
        self._warm_spec(prompt_len)
        results: Dict[str, RequestResult] = {}
        done = threading.Event()

        def deliver(res: RequestResult) -> None:
            results[res.request_id] = res
            if len(results) == num_requests:
                done.set()

        q: 'queue.Queue[Request]' = queue.Queue()
        stop = threading.Event()
        loop = threading.Thread(
            target=self.generate_stream, args=(q, deliver, stop),
            daemon=True)
        t0 = time.time()
        loop.start()
        gaps = (rng.exponential(1.0 / qps, size=num_requests)
                if qps else np.zeros(num_requests))
        for req, gap in zip(reqs, gaps):
            time.sleep(float(gap))
            req.arrival_time = time.time()
            q.put(req)
        # Progress-aware stall detection (replaces a hard-coded 3600 s
        # wait): a dead or wedged serving loop is declared after ONE
        # completion-free run_stall_timeout_s window, while a healthy
        # long run just keeps resetting the window with every finish.
        stall_s = self.cfg.run_stall_timeout_s
        last_done = 0
        while not done.wait(timeout=stall_s):
            if len(results) == last_done:
                stop.set()
                loop.join(timeout=30)
                raise RuntimeError(
                    f'serving stalled: {len(results)}/{num_requests} '
                    f'requests finished, none in the last '
                    f'{stall_s:.0f}s (InferConfig.run_stall_timeout_s);'
                    f' engine stats: {self.stats()}')
            last_done = len(results)
        stop.set()
        loop.join(timeout=30)
        elapsed = time.time() - t0
        if not results:
            # Unreachable once done fired, but keep the loud failure
            # over an IndexError below if the accounting ever breaks.
            raise RuntimeError(
                f'serving benchmark incomplete: {len(results)}/'
                f'{num_requests} requests finished in {elapsed:.0f}s')
        out_tokens = sum(len(r.output_tokens) for r in results.values())
        in_tokens = sum(len(r.prompt_tokens) for r in results.values())
        ttfts = sorted(r.ttft_s for r in results.values())
        tpots = sorted(
            (r.latency_s - r.ttft_s) / max(len(r.output_tokens) - 1, 1)
            for r in results.values())

        def pct(xs, p):
            return xs[min(len(xs) - 1, int(len(xs) * p))]

        return {
            'requests_per_second': len(results) / elapsed,
            'output_tokens_per_second': out_tokens / elapsed,
            'input_tokens_per_second': in_tokens / elapsed,
            'ttft_median_s': pct(ttfts, 0.5),
            'ttft_p99_s': pct(ttfts, 0.99),
            'tpot_median_s': pct(tpots, 0.5),
            'tpot_p99_s': pct(tpots, 0.99),
            # None (JSON null) when no arrival rate was set: float('inf')
            # serializes as the non-standard token 'Infinity' that strict
            # parsers (jq) reject.
            'offered_qps': qps if qps else None,
            'completed': len(results),
            'elapsed_s': elapsed,
        }

    def benchmark(self, num_requests: int = 32, prompt_len: int = 128,
                  new_tokens: int = 64,
                  seed: int = 0) -> Dict[str, float]:
        """Synthetic serving benchmark: JetStream-comparable metrics
        (req/s, output tok/s, TTFT) on random prompts."""
        rng = np.random.default_rng(seed)
        reqs = [
            Request(tokens=rng.integers(
                0, self.model_config.vocab_size,
                size=prompt_len).tolist(),
                    max_new_tokens=new_tokens)
            for _ in range(num_requests)
        ]
        # Warmup/compile with a full-length request so the timed run hits
        # the same prefill bucket (no jit compile inside the measurement).
        self.warmup_decode(reqs[0].tokens)
        self._warm_spec(prompt_len)
        t0 = time.time()
        results = self.generate(reqs)
        elapsed = time.time() - t0
        out_tokens = sum(len(r.output_tokens) for r in results)
        in_tokens = sum(len(r.prompt_tokens) for r in results)
        ttfts = sorted(r.ttft_s for r in results)
        return {
            'requests_per_second': num_requests / elapsed,
            'output_tokens_per_second': out_tokens / elapsed,
            'input_tokens_per_second': in_tokens / elapsed,
            'ttft_median_s': ttfts[len(ttfts) // 2],
            'ttft_p99_s': ttfts[min(len(ttfts) - 1,
                                    int(len(ttfts) * 0.99))],
            'elapsed_s': elapsed,
        }


def engine_from_name(model: str, cfg: Optional[InferConfig] = None,
                     rng: Optional[jax.Array] = None) -> InferenceEngine:
    from skypilot_tpu.models import get_model_config
    model_config = get_model_config(model)
    cfg = cfg or InferConfig(model=model)
    return InferenceEngine(model_config, cfg, rng=rng)
