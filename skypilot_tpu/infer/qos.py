"""Engine-side QoS: priority classes, per-tenant weighted-fair
queueing, and the service-rate estimator behind deadline-driven
admission shedding.

Overload is the steady state for a public serve plane (ROADMAP item
3), and FIFO admission under overload gives every tenant the same bad
tail.  This module replaces the FIFO seam (`infer/scheduler.py`) with:

- **Priority classes** — `interactive` strictly ahead of `batch`
  (extensible: the class list is data, not control flow).  Interactive
  arrivals additionally preempt part-prefilled batch work at
  chunked-prefill boundaries (engine `_maybe_preempt_for`): the parked
  prompt's paged blocks stay refcounted in the radix tree, so resume
  is a suffix-only prefill, not lost work.
- **Weighted-fair queueing** within a class — classic virtual-time
  WFQ keyed on `Request.tenant_id`.  Each tenant's lane is FIFO; a
  lane's entries carry virtual FINISH tags `max(V, lane_tail) +
  cost/weight`; pop takes the smallest tag across lanes and advances
  the class virtual clock V to it.  Cost is the request's token work
  (prompt + max_new), so fairness is in *service share*, not request
  count — ten small requests and one big one cost the same budget.
- **Deadline shedding** — `ServiceEstimator` keeps an EWMA of the
  observed per-request service rate (tokens/s, prompt+output, fed by
  every completed request).  At dequeue the engine rejects work whose
  elapsed queue time + projected (prefill + decode) time cannot meet
  its `deadline_s`: a typed immediate rejection
  (finish_reason='deadline', error_class='shed') instead of burning a
  prefill on a result nobody will read.

Layering: this is an INFER module — it must never import
`skypilot_tpu.serve` (the LB-side token buckets live in
`serve/qos.py`).  No wall clocks in here either: WFQ time is virtual
(work-based) and the estimator is fed durations by the engine.
"""
import collections
import threading
from typing import Any, Dict, Optional, TYPE_CHECKING

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.infer.scheduler import Scheduler

if TYPE_CHECKING:                     # import cycle guard: engine.py
    from skypilot_tpu.infer.engine import Request  # pragma: no cover

# Strict-priority order, highest first.  classify() maps unknown /
# unset priorities to the FIRST class so a plain request is never
# accidentally demoted; the server validates the field at the edge.
PRIORITY_CLASSES = ('interactive', 'batch')

# Tenant key for requests without a tenant_id: they all share ONE
# default lane (weight 1.0) rather than bypassing fairness.
DEFAULT_TENANT = '_default'


def classify(req: 'Request') -> str:
    """Priority class of a request ('interactive' unless explicitly
    'batch' — see PRIORITY_CLASSES)."""
    p = getattr(req, 'priority', None)
    return p if p in PRIORITY_CLASSES else PRIORITY_CLASSES[0]


class ServiceEstimator:
    """EWMA of the engine's observed service rate, in tokens/second
    per request (prompt + generated, end to end including queueing at
    the device).  Deliberately coarse: the shedding bound wants a
    stable order-of-magnitude answer, not a per-shape model.  Returns
    None until it has seen at least one completion — with no signal
    the engine never sheds on projection (only on already-expired
    deadlines)."""

    def __init__(self, alpha: float = 0.3) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f'alpha must be in (0, 1] (got {alpha})')
        self._alpha = alpha
        self._rate: Optional[float] = None   # tokens / second

    def observe(self, tokens: int, seconds: float) -> None:
        """Feed one completed request's token work and wall duration."""
        if tokens <= 0 or seconds <= 0.0:
            return
        r = tokens / seconds
        self._rate = r if self._rate is None else (
            self._alpha * r + (1.0 - self._alpha) * self._rate)

    def rate(self) -> Optional[float]:
        return self._rate

    def projected_s(self, tokens: int) -> Optional[float]:
        """Projected service seconds for `tokens` of work, or None
        when no completion has been observed yet."""
        if self._rate is None or self._rate <= 0.0 or tokens <= 0:
            return None
        return tokens / self._rate


class _Lane:
    """One tenant's FIFO lane inside a class: (finish_tag, req) deque
    plus the tail finish tag future pushes chain behind."""
    __slots__ = ('entries', 'tail')

    def __init__(self) -> None:
        self.entries: collections.deque = collections.deque()
        self.tail = 0.0


class WfqScheduler(Scheduler):
    """Strict priority across PRIORITY_CLASSES; virtual-time WFQ over
    tenant lanes within each class.  Plugs into the engine behind the
    `infer/scheduler.py` seam (`InferConfig.qos = True`)."""

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 cost_fn=None) -> None:
        ws = dict(weights or {})
        for t, w in ws.items():
            if not (isinstance(w, (int, float)) and w > 0):
                raise ValueError(
                    f'tenant weight must be > 0 (tenant {t!r}: {w!r})')
        self._weights = ws
        # Cost of a request in virtual-time units; the engine passes
        # its token-work measure (prompt + resolved max_new).
        self._cost_fn = cost_fn or (
            lambda r: len(r.tokens) + (r.max_new_tokens or 1))
        # Per-class: virtual clock + tenant lanes.  All guarded — pop
        # runs on the loop thread while stats()/backlog() may be read
        # from the HTTP threads.
        self._vtime: Dict[str, float] = {  # guarded-by: _lock
            c: 0.0 for c in PRIORITY_CLASSES}
        self._lanes: Dict[str, Dict[str, _Lane]] = {  # guarded-by: _lock
            c: {} for c in PRIORITY_CLASSES}
        self._depth = 0  # guarded-by: _lock
        # Work admitted through pop(), in cost units per tenant —
        # the fairness tests measure share against this.
        self.served: Dict[str, float] = {}  # guarded-by: _lock
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.qos.wfq._lock')

    def _tenant(self, req: 'Request') -> str:
        t = getattr(req, 'tenant_id', None)
        return t if t else DEFAULT_TENANT

    def weight(self, tenant: str) -> float:
        return float(self._weights.get(tenant, 1.0))

    def push(self, req: 'Request') -> None:
        cls, tenant = classify(req), self._tenant(req)
        cost = float(self._cost_fn(req))
        with self._lock:
            lane = self._lanes[cls].setdefault(tenant, _Lane())
            tag = max(self._vtime[cls], lane.tail) \
                + cost / self.weight(tenant)
            lane.tail = tag
            lane.entries.append((tag, req))
            self._depth += 1

    def pop(self) -> Optional['Request']:
        with self._lock:
            for cls in PRIORITY_CLASSES:
                lanes = self._lanes[cls]
                best = None
                for tenant, lane in lanes.items():
                    if lane.entries and (
                            best is None or
                            lane.entries[0][0] < lanes[best].entries[0][0]):
                        best = tenant
                if best is None:
                    continue
                tag, req = lanes[best].entries.popleft()
                self._vtime[cls] = max(self._vtime[cls], tag)
                self._depth -= 1
                self.served[best] = self.served.get(best, 0.0) \
                    + float(self._cost_fn(req))
                return req
            return None

    def _over_share(self, tenant: str) -> float:
        """Weight-normalised service already consumed: the fairness
        measure shed ordering ranks by."""
        return self.served.get(tenant, 0.0) / self.weight(tenant)

    def shed_victim(self, prefer_over: Optional[str] = None,
                    doomed=None) -> Optional['Request']:
        """Deadline-shedding victim selection (parked since PR 8):
        when the projection bound says work must be dropped, drop the
        MOST-over-fair-share tenant's most recent deadline-bearing row
        — batch class before interactive, lane tail before lane head —
        instead of whatever FIFO/WFQ pop order happens to surface
        (which punishes the under-share tenant at the head).

        Only requests carrying a deadline_s are eligible (no-deadline
        work is never shed), and `doomed(req)` — when given — must
        also confirm the candidate cannot meet its own deadline, so
        fairness never sacrifices a row that would have made it.  With
        `prefer_over` set, only tenants STRICTLY more over-share than
        that tenant qualify; None then means "shed the caller's own
        request instead".  The removed request is returned un-charged
        (its push() cost stands; shedding is not service)."""
        with self._lock:
            floor = (self._over_share(prefer_over)
                     if prefer_over is not None else None)
            best = None        # (share, cls_idx, pos_in_lane)
            for cls_idx, cls in enumerate(reversed(PRIORITY_CLASSES)):
                for tenant, lane in self._lanes[cls].items():
                    if not lane.entries:
                        continue
                    share = self._over_share(tenant)
                    if floor is not None and share <= floor:
                        continue
                    for pos in range(len(lane.entries) - 1, -1, -1):
                        req = lane.entries[pos][1]
                        if getattr(req, 'deadline_s', None) is None:
                            continue
                        if doomed is not None and not doomed(req):
                            continue
                        cand = (share, -cls_idx, pos)
                        if best is None or cand > best[0]:
                            best = (cand, cls, tenant, pos)
                        break      # most recent eligible in this lane
            if best is None:
                return None
            _, cls, tenant, pos = best
            lane = self._lanes[cls][tenant]
            _, victim = lane.entries[pos]
            del lane.entries[pos]
            self._depth -= 1
            return victim

    def requeue(self, req: 'Request') -> None:
        """Preempted work re-enters at the FRONT of its lane with the
        class's current virtual time: immediately eligible again, and
        not re-charged — its cost was spent at push()."""
        cls, tenant = classify(req), self._tenant(req)
        with self._lock:
            lane = self._lanes[cls].setdefault(tenant, _Lane())
            lane.entries.appendleft((self._vtime[cls], req))
            self._depth += 1

    def backlog(self) -> int:
        return self._depth

    def waiting(self, priority: str) -> int:
        with self._lock:
            return sum(len(lane.entries)
                       for lane in self._lanes.get(priority, {}).values())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            depth = {cls: sum(len(lane.entries)
                              for lane in self._lanes[cls].values())
                     for cls in PRIORITY_CLASSES}
            tenants: Dict[str, Dict[str, Any]] = {}
            for cls in PRIORITY_CLASSES:
                for tenant, lane in self._lanes[cls].items():
                    t = tenants.setdefault(
                        tenant, {'queued': 0,
                                 'weight': self.weight(tenant),
                                 'served_cost': self.served.get(
                                     tenant, 0.0)})
                    t['queued'] += len(lane.entries)
            for tenant, cost in self.served.items():
                tenants.setdefault(
                    tenant, {'queued': 0, 'weight': self.weight(tenant),
                             'served_cost': cost})
            return {'policy': 'wfq', 'depth': depth, 'tenants': tenants}
