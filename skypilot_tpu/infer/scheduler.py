"""Admission-order policy seam for the serving loop (ROADMAP item 5).

The engine's `_serve_loop` historically pulled straight off the client
request queue — FIFO head-of-line admission baked into the loop body.
This module extracts the ORDERING policy behind a small interface so
alternative schedulers (the per-tenant weighted-fair queue in
`infer/qos.py`) plug in without growing more inline engine code:

    loop drains request_queue -> Scheduler.push()
    loop asks Scheduler.pop() for the next request to admit
    preempted / parked work re-enters via Scheduler.requeue()

What stays ENGINE-side on purpose: paged-pool admission control
(`_deferred` keeps strict head-of-line so a big request is never
starved by small ones that keep fitting around it), request
validation, cancellation, and deadline enforcement.  The scheduler
decides only *which queued request is next*.

Thread model: push/pop/requeue run on the serving-loop thread;
backlog()/stats() may be called from any thread (server /stats), so
every scheduler carries its own small lock — never call back into the
engine from inside a scheduler (the engine lock may be held around
requeue()).
"""
import collections
import threading
from typing import Any, Dict, List, Optional, TYPE_CHECKING

from skypilot_tpu.analysis import sanitizers

if TYPE_CHECKING:                     # import cycle guard: engine.py
    from skypilot_tpu.infer.engine import Request  # pragma: no cover


class Scheduler:
    """Interface: which queued request does the engine admit next?"""

    def push(self, req: 'Request') -> None:
        """A request arrived (drained off the client queue)."""
        raise NotImplementedError

    def pop(self) -> Optional['Request']:
        """Next request to admit, or None when nothing is queued."""
        raise NotImplementedError

    def requeue(self, req: 'Request') -> None:
        """Give back a request the engine could not (or chose not to)
        run yet — preempted chunk jobs re-enter here.  Must make the
        request eligible again without re-charging its queueing cost."""
        raise NotImplementedError

    def backlog(self) -> int:
        """Queued requests (feeds the engine's arrivals hint)."""
        raise NotImplementedError

    def waiting(self, priority: str) -> int:
        """Queued requests of the given priority class (0 for
        schedulers without class lanes) — the preemption trigger."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Any]:
        raise NotImplementedError


class FifoScheduler(Scheduler):
    """The historical policy, verbatim: strict arrival order, one
    lane, no classes.  The default (`InferConfig.qos = False`) — byte-
    identical admission order to the pre-seam serving loop."""

    def __init__(self) -> None:
        self._q: collections.deque = collections.deque()  # guarded-by: _lock
        self._lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.scheduler.fifo._lock')

    def push(self, req: 'Request') -> None:
        with self._lock:
            self._q.append(req)

    def pop(self) -> Optional['Request']:
        with self._lock:
            return self._q.popleft() if self._q else None

    def requeue(self, req: 'Request') -> None:
        with self._lock:
            self._q.appendleft(req)

    def backlog(self) -> int:
        return len(self._q)

    def waiting(self, priority: str) -> int:
        return 0                      # no class lanes in FIFO

    def stats(self) -> Dict[str, Any]:
        return {'policy': 'fifo', 'depth': {'all': len(self._q)}}
