"""Deterministic fault injection for the serving engine.

The reference system's value proposition is surviving failure (spot
preemption recovery, failover, replica health), and the serving data
plane must hold the same bar: degrade per-request, never per-process.
Proving that requires *reproducible* failures — a chaos test that only
fails once a week is worse than no test.  This module gives the engine
named injection sites it consults through one attribute check:

    plan = FaultPlan(seed=7, specs=[
        FaultSpec(site='decode_step', hits=(2,), slot=1),
    ])
    engine.arm_faults(plan)

Design rules:

- **Zero overhead unarmed.**  Every site costs exactly one
  ``self._faults is None`` check when no plan is armed; no RNG draw,
  no counter, no lock.
- **Fully reproducible armed.**  Firing is a pure function of the
  plan's seed and the per-site consult sequence.  ``hits`` fires on
  exact 1-based consult indices; ``prob`` draws one Bernoulli per
  consult from a per-spec ``numpy`` Generator seeded from
  ``(seed, spec index)`` — so two runs with the same plan and the same
  request stream fire identically, and specs never perturb each
  other's streams.
- **Attribution is part of the fault.**  A raised :class:`InjectedFault`
  can carry the slot(s) it claims to have injured; the engine's
  containment path uses that to fail only those requests.  Faults
  without attribution exercise the quarantine-the-batch fallback.

Sites (where the engine consults the plan):

==================  =====================================================
``prefill``         top of ``_start_batch``, before the prefill dispatch
``decode_step``     top of ``_step``, before the decode-window dispatch
``chunk_round``     top of ``_chunk_round`` when chunk jobs exist
``block_alloc``     inside ``_can_admit_blocks`` — a firing spec forces
                    the admission answer to "no" (defer), modelling a
                    transiently exhausted pool rather than a crash
``nonfinite_logits``  after the decode window's host unpack — a firing
                    spec overwrites one lane's logprobs with NaN to
                    exercise the non-finite guard
``stall``           top of each serving-loop iteration — a firing spec
                    sleeps ``stall_s`` to exercise stall detection
``serve_loop``      top of serving-loop iterations that have active
                    slots or chunk jobs — a firing spec raises OUTSIDE
                    every contained region, killing the loop thread to
                    exercise the supervisor (conditioning on active
                    work makes "hit 1" deterministic with respect to
                    request state instead of racing the idle spin)
``replica_kill``    PROCESS-level site, consulted by the multi-replica
                    chaos harness (`infer/chaos.py` killer thread, not
                    the engine): a firing spec kills one live replica —
                    listener closed, in-flight client sockets severed,
                    serving loop stopped — to exercise the load
                    balancer's circuit breaker and mid-stream failover.
                    The harness never kills the last live replica, and
                    caps kills with ``max_fires``
``net_degrade``     NETWORK-level site, consulted by the chaos
                    harness's ``DegradedReplica`` proxy once per
                    server→client chunk: a firing spec injects
                    ``delay_s`` (± ``jitter_s``, seeded) before the
                    chunk is relayed, or swallows it entirely when
                    ``blackhole`` — a gray failure (alive but slow/
                    lossy) to exercise the LB's probation track
``lb_kill``         CONTROL-PLANE site, consulted by the chaos
                    harness's killer thread: a firing spec kills the
                    load balancer itself (listener closed, in-flight
                    proxies severed) to exercise supervisor restart +
                    warm-journal re-adoption
==================  =====================================================

Injected dispatch faults are raised HOST-SIDE, before the jitted call:
a jitted call that fails after buffer donation can invalidate the KV
cache, which would break the survivors-byte-identical guarantee the
chaos tests assert.  (A *real* post-donation device failure is exactly
the unattributed case: the engine quarantines the batch and rebuilds
the cache.)
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

SITES = (
    'prefill',
    'decode_step',
    'chunk_round',
    'block_alloc',
    'nonfinite_logits',
    'stall',
    'serve_loop',
    'replica_kill',
    'net_degrade',
    'lb_kill',
)


class InjectedFault(RuntimeError):
    """Raised by the engine at a fault site the armed plan fired on.

    ``slots`` is the injected attribution: the engine slot indices the
    fault claims to have injured (None = unattributed, which makes the
    containment path quarantine every active slot).
    """

    def __init__(self, message: str, site: str,
                 slots: Optional[Sequence[int]] = None):
        super().__init__(message)
        self.site = site
        self.slots = None if slots is None else [int(s) for s in slots]


@dataclasses.dataclass
class FaultSpec:
    """One deterministic failure rule at a named site.

    site       one of :data:`SITES`.
    hits       1-based consult indices of the site at which this spec
               fires (e.g. ``(2,)`` = the second time the engine
               consults the site).  Exact and reproducible.
    prob       when ``hits`` is None: per-consult Bernoulli firing
               probability, drawn from the spec's own seeded stream.
    max_fires  stop firing after this many fires (None = unlimited;
               ``hits`` specs are naturally bounded by ``len(hits)``).
    slot       attribution: the engine slot this fault claims to have
               injured (None = unattributed → batch quarantine).
    stall_s    for the ``stall`` site: how long the loop sleeps.
    delay_s    for the ``net_degrade`` site: base added latency per
               relayed chunk (gray failure, not a crash).
    jitter_s   for the ``net_degrade`` site: uniform ±jitter around
               ``delay_s``, drawn from the consulting harness's own
               seeded stream (spec streams stay consult-aligned).
    blackhole  for the ``net_degrade`` site: a firing consult swallows
               the chunk instead of delaying it (lossy path).
    message    human-readable tag carried into the raised error.
    """

    site: str
    hits: Optional[Tuple[int, ...]] = None
    prob: float = 0.0
    max_fires: Optional[int] = None
    slot: Optional[int] = None
    stall_s: float = 0.0
    delay_s: float = 0.0
    jitter_s: float = 0.0
    blackhole: bool = False
    message: str = 'injected fault'

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f'unknown fault site {self.site!r}; valid sites: {SITES}')
        if self.hits is not None:
            self.hits = tuple(int(h) for h in self.hits)
            if any(h < 1 for h in self.hits):
                raise ValueError('hits are 1-based consult indices (>= 1)')
        if not 0.0 <= self.prob <= 1.0:
            raise ValueError(f'prob must be in [0, 1] (got {self.prob})')
        if self.hits is None and self.prob == 0.0:
            raise ValueError('spec can never fire: give hits or prob > 0')
        if self.delay_s < 0.0 or self.jitter_s < 0.0:
            raise ValueError('delay_s/jitter_s must be >= 0')
        if self.jitter_s > self.delay_s and self.jitter_s > 0.0:
            raise ValueError('jitter_s must not exceed delay_s '
                             '(delay - jitter would go negative)')
        if ((self.delay_s > 0.0 or self.blackhole)
                and self.site != 'net_degrade'):
            raise ValueError(
                'delay_s/blackhole only apply to the net_degrade site')


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules the engine consults.

    Thread-safe: the serving loop and ``benchmark_serving``'s feeder
    consult concurrently.  ``consults``/``fired`` expose per-site
    counters for tests and the chaos smoke's accounting.
    """

    def __init__(self, seed: int = 0,
                 specs: Sequence[FaultSpec] = ()):
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)
        self._lock = threading.Lock()
        self.consults: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        # Per-spec state: independent RNG stream (so spec ordering and
        # other sites' consult volume never shift a spec's draws) and
        # a fire counter for max_fires.
        self._rngs = [np.random.default_rng((self.seed, i))
                      for i in range(len(self.specs))]
        self._fires = [0] * len(self.specs)
        self._by_site: Dict[str, List[int]] = {}
        for i, sp in enumerate(self.specs):
            self._by_site.setdefault(sp.site, []).append(i)

    def check(self, site: str) -> Optional[FaultSpec]:
        """One consult of ``site``; returns the firing spec, else None.

        Every consult advances the site's counter and (for prob specs
        at this site) their RNG streams, whether or not anything fires
        — firing is a pure function of the consult sequence.
        """
        with self._lock:
            n = self.consults.get(site, 0) + 1
            self.consults[site] = n
            hit: Optional[FaultSpec] = None
            for i in self._by_site.get(site, ()):
                sp = self.specs[i]
                if sp.hits is not None:
                    fires = n in sp.hits
                else:
                    # Always draw: keeps the stream aligned to the
                    # consult index even when max_fires already tripped.
                    fires = float(self._rngs[i].random()) < sp.prob
                if (sp.max_fires is not None
                        and self._fires[i] >= sp.max_fires):
                    fires = False
                if fires and hit is None:
                    self._fires[i] += 1
                    self.fired[site] = self.fired.get(site, 0) + 1
                    hit = sp
            return hit

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {'consults': dict(self.consults),
                    'fired': dict(self.fired)}
