"""HTTP inference server: the process a serve-plane replica runs.

`python -m skypilot_tpu.infer.server --model llama-debug --port 8100`

Endpoints:
  GET  /health    -> 200 {"status": "ok"} once the engine is compiled
                     (the serve plane's readiness prober hits this).
  POST /generate  -> {"tokens": [...], "max_new_tokens": N,
                      "temperature": T}
                  <- {"output_tokens": [...], "ttft_s": ..., ...}
  POST /generate_text (when --tokenizer is given: HF tokenizer name)
  POST /cache_prefix -> {"tokens": [...]} (or {"prompt": "..."} with a
                     tokenizer): pin a system prompt's KV on device so
                     matching prompts prefill suffix-only (lower TTFT).
  OpenAI-compatible surface (drop-in for clients written against the
  reference's vLLM recipes, llm/vllm/README.md curl examples):
  POST /v1/completions        prompt (string or token array), max_tokens,
                              temperature, stop, stream (SSE + [DONE])
  POST /v1/chat/completions   messages via the tokenizer's chat template
  GET  /v1/models             the served model id + loaded adapters
  GET  /stats                 slots/queue/shed/spec/prefix counters
  POST /load_adapter -> {"name": ..., "path": "adapter.npz"}: load a
                     trained LoRA adapter (train.lora.save_adapter_npz)
                     into a stack slot; requests select it via
                     "adapter" or the OpenAI "model" field — concurrent
                     requests for different adapters decode in one
                     batch (the reference's LoRAX recipe, llm/lorax/).

stdlib-only (ThreadingHTTPServer): requests block their handler thread on
a per-request event while the single engine thread runs continuous
batching across all in-flight requests.

Role parity: the replica-side counterpart of the reference's vLLM/
JetStream server recipes (llm/vllm/serve.yaml, examples/tpu/v6e/).
"""
import argparse
import json
import os
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from skypilot_tpu.analysis import sanitizers
from skypilot_tpu.infer import qos as qos_mod
from skypilot_tpu.infer.engine import (InferConfig, InferenceEngine,
                                       Request, RequestResult,
                                       resolve_cache_dtype)


class DrainingError(Exception):
    """Request refused because the replica is draining (graceful
    shutdown): it stopped admitting new generate work and is finishing
    what it already accepted.  The HTTP layer turns this into a 503
    with Retry-After and an ``X-SkyTpu-Draining: 1`` header so the load
    balancer retries elsewhere instead of surfacing the 503."""


class AdmissionError(Exception):
    """Request shed at admission (TTFT bound exceeded or queue full)."""

    def __init__(self, projected_s: float, bound_s: float,
                 retry_after_s: Optional[int] = None,
                 message: Optional[str] = None):
        self.projected_s = projected_s
        self.bound_s = bound_s
        # Explicit Retry-After override (the queue-cap shed computes its
        # own drain estimate; the TTFT shed derives one from the bound).
        self.retry_after_s = retry_after_s
        super().__init__(
            message or
            f'overloaded: recent TTFT {projected_s:.1f}s exceeds the '
            f'{bound_s:.1f}s admission bound')


class InferenceServer:

    def __init__(self, engine: InferenceEngine,
                 tokenizer: Optional[object] = None,
                 max_projected_ttft_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 adapter_dir: Optional[str] = None,
                 auto_prefix: bool = False,
                 warmup: bool = False):
        """max_projected_ttft_s: admission bound (VERDICT r2 weak #5) —
        shed (AdmissionError -> HTTP 429 + Retry-After) instead of
        queueing while the server is past the bound.  Feedback control
        on OBSERVED time-to-first-token: shed while the median TTFT of
        recent completions exceeds the bound and a queue actually
        exists.  (A rate-based feedforward projection was tried first
        and rejected: any completion-cadence estimate conflates arrival
        rate with service capacity whenever traffic is below
        saturation, producing false sheds after idle periods.)  None =
        admit everything (unbounded queue wait).

        max_queue: hard first-token backlog cap — sheds the moment the
        backlog reaches it while slots are saturated (feedforward; no
        lag, no estimation).  The TTFT bound reacts to what HAPPENED;
        the queue cap bounds what CAN happen — together they bound
        both the median and the tail."""
        self.engine = engine
        self.tokenizer = tokenizer
        self.max_projected_ttft_s = max_projected_ttft_s
        self.max_queue = max_queue
        # POST /load_adapter reads files named by UNAUTHENTICATED
        # clients (default bind 0.0.0.0): confine it to this directory
        # (None = runtime adapter loading disabled).  The vLLM analog
        # is VLLM_ALLOW_RUNTIME_LORA_UPDATING.
        self.adapter_dir = adapter_dir
        # Automatic prefix caching (vLLM-APC analog, opt-in): when the
        # same prompt HEAD shows up twice, register it as a prefix so
        # later matching requests (once the background capture lands)
        # prefill suffix-only.  Heads are
        # quantized to PREFILL BUCKET lengths — the engine's
        # prefix-prefill compiles per (start, suffix-bucket), so
        # arbitrary auto lengths would explode the jit-key space;
        # bucket boundaries keep it to O(#buckets) like everything
        # else.  Registration runs in a background thread (one device
        # forward + possible compile) so no request waits on it.
        self.auto_prefix = auto_prefix
        # Deterministic warmup-on-boot: drive EVERY enumerated jit
        # root×bucket shape (engine.warmup(), the COMPILE pass's shape
        # space) before declaring ready, so a fresh scale-up replica
        # serves its first request at steady-state TTFT.  Off by
        # default: it multiplies boot time by the full compile space.
        self.warmup = warmup
        self._auto_lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.server._auto_lock')
        self._auto_counts: Dict[tuple, int] = {}
        self._auto_inflight: set = set()
        self._auto_failed: set = set()
        self.ready = threading.Event()
        self._queue: 'queue.Queue[Request]' = queue.Queue()
        self._results: Dict[str, RequestResult] = {}
        self._events: Dict[str, threading.Event] = {}
        self._stream_queues: Dict[str, 'queue.Queue'] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        # Admission bookkeeping: requests admitted but first-token-less,
        # and the observed TTFTs of recent completions.
        self._adm_lock = sanitizers.instrument_lock(
            threading.Lock(), 'infer.server._adm_lock')
        self._awaiting_first: set = set()
        import collections
        self._recent_ttfts: 'collections.deque' = collections.deque(
            maxlen=16)
        self.shed_count = 0
        # Graceful drain (POST /drain, SIGTERM): once draining, new
        # generate requests are refused with 503 + Retry-After while
        # the ones already in flight run to completion; `drained` fires
        # when the last one leaves (or the drain deadline passes).
        # _gen_inflight counts generate-endpoint HTTP requests between
        # begin_generate/end_generate — the unit a drain must finish.
        self.draining = threading.Event()
        self.drained = threading.Event()
        self._gen_inflight = 0
        self.drain_refused = 0          # 503s answered while draining
        self._on_drained = None         # callback once drain completes

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def _run(self) -> None:
        # Compile before declaring ready so the first real request does
        # not eat the (tens of seconds) jit cost — including BOTH decode
        # window variants when the adaptive window is on (a single
        # warmup request only compiles the short one).  --warmup walks
        # the FULL enumerated root×bucket shape space instead (steady-
        # state TTFT from the first request, at the cost of boot time).
        if self.warmup:
            self.engine.warmup()
        else:
            self.engine.warmup_decode([1, 2, 3])
        self.ready.set()
        self.engine.generate_stream(self._queue, self._deliver, self._stop)

    def _deliver(self, res: RequestResult) -> None:
        rid = res.request_id
        if rid is None:
            return
        sq = self._stream_queues.get(rid)
        if sq is not None:          # streaming request: sentinel in-band
            sq.put(('done', res))
            return
        # Store BEFORE checking the event: if the waiter times out
        # between our check and store, it pops _results after popping
        # _events, so either it takes the result or we remove it below —
        # no ordering leaks entries.
        self._results[rid] = res
        ev = self._events.get(rid)
        if ev is None:
            self._results.pop(rid, None)   # abandoned: drop
            return
        ev.set()

    # ---------------------------------------------------------- admission

    _ADMIT_BACKLOG_FLOOR = 4

    def _admit(self, rid: str) -> None:
        """Raise AdmissionError while the server is past its TTFT bound;
        otherwise record the request as awaiting first token.

        Sheds only when (a) the median OBSERVED TTFT of recent
        completions exceeds the bound — under-saturated traffic
        completes fast, so an idle server or an absorbable burst never
        sheds — and (b) a queue actually exists: every decode slot is
        occupied (engine saturation peek) and the first-token backlog
        is past a small floor.  A hot TTFT window with free slots must
        not shed — those are echoes of a drained queue.  Completions
        made during shedding carry the queue's high TTFTs, so shedding
        holds until the queue has genuinely drained (deliberate
        hysteresis, bounded by the saturation check)."""
        bound = self.max_projected_ttft_s
        with self._adm_lock:
            backlog = len(self._awaiting_first)
            saturated = (self.engine is None or
                         not self.engine.has_free_slot())
            if (self.max_queue is not None and saturated and
                    backlog >= self.max_queue):
                import math
                import statistics
                self.shed_count += 1
                # Drain estimate: the queue moves at roughly one
                # first-token per recent-TTFT/backlog... the honest
                # cheap signal is the recent TTFT itself (how long the
                # queue has been making requests wait).
                est = (statistics.median(self._recent_ttfts)
                       if self._recent_ttfts else None)
                retry = max(1, math.ceil(est)) if est is not None else 5
                raise AdmissionError(
                    est if est is not None else 0.0,
                    bound if bound is not None else 0.0,
                    retry_after_s=retry,
                    message=f'overloaded: admission queue full '
                            f'({backlog} requests waiting, cap '
                            f'{self.max_queue})')
            if (bound is not None and saturated and
                    backlog >= self._ADMIT_BACKLOG_FLOOR and
                    len(self._recent_ttfts) >= 4):
                import statistics
                med = statistics.median(self._recent_ttfts)
                if med > bound:
                    self.shed_count += 1
                    raise AdmissionError(med, bound)
            self._awaiting_first.add(rid)

    def _note_first_token(self, rid: str,
                          ttft_s: Optional[float] = None) -> None:
        with self._adm_lock:
            if rid in self._awaiting_first:
                self._awaiting_first.discard(rid)
                if ttft_s is not None:
                    self._recent_ttfts.append(ttft_s)

    def _drop_admitted(self, rid: str) -> None:
        """Request left the system without a first token (error/timeout):
        remove from the backlog WITHOUT counting a service completion."""
        with self._adm_lock:
            self._awaiting_first.discard(rid)

    # ------------------------------------------------------ graceful drain

    def begin_generate(self) -> bool:
        """Admit one generate-endpoint HTTP request into the drain
        accounting; False = draining (caller answers 503)."""
        with self._adm_lock:
            if self.draining.is_set():
                self.drain_refused += 1
                return False
            self._gen_inflight += 1
            return True

    def end_generate(self) -> None:
        with self._adm_lock:
            self._gen_inflight = max(0, self._gen_inflight - 1)
            done = (self.draining.is_set() and self._gen_inflight == 0
                    and not self.drained.is_set())
            if done:
                self.drained.set()
        if done:
            self._fire_on_drained()

    @property
    def gen_inflight(self) -> int:
        with self._adm_lock:
            return self._gen_inflight

    def _fire_on_drained(self) -> None:
        cb = self._on_drained
        if cb is not None:
            # Off-thread: the callback typically shuts the HTTP server
            # down, which must not deadlock against the handler thread
            # that delivered the last in-flight completion.
            threading.Thread(target=cb, daemon=True).start()

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """Stop admitting generate work (503 + Retry-After); finish
        what is in flight, then fire `drained` (and _on_drained).  With
        a deadline, `drained` fires after deadline_s even if stragglers
        remain — the teardown that follows was going to kill them
        anyway, and a bound drain beats an unbounded wait on a wedged
        request.  Idempotent."""
        with self._adm_lock:
            already = self.draining.is_set()
            self.draining.set()
            empty = self._gen_inflight == 0 and not self.drained.is_set()
            if empty:
                self.drained.set()
        if empty:
            self._fire_on_drained()
        if already:
            return
        if deadline_s is not None and not self.drained.is_set():
            def watchdog():
                if not self.drained.wait(deadline_s):
                    self.drained.set()
                    self._fire_on_drained()
            threading.Thread(target=watchdog, daemon=True).start()

    def undrain(self) -> None:
        """Cancel a drain (tests; an operator changing their mind
        before teardown).  Admission resumes immediately."""
        with self._adm_lock:
            self.draining.clear()
            self.drained.clear()

    def health(self) -> Dict[str, object]:
        """The /healthz readiness document: loop-alive / model-ready /
        draining, derived from the engine's serving flag + stats().

        status: 'ok' (route traffic here), 'starting' (still
        compiling), 'draining' (finishing in-flight, admit nothing
        new), 'dead' (the engine's serving-loop supervisor gave up —
        the process is up but can never answer another generate)."""
        model_ready = self.ready.is_set()
        serving = bool(getattr(self.engine, 'serving', True))
        # Before ready fires the loop has legitimately not started yet;
        # only a loop that died AFTER readiness means 'dead'.
        loop_alive = serving or not model_ready
        if not model_ready:
            status = 'starting'
        elif not serving:
            status = 'dead'
        elif self.draining.is_set():
            status = 'draining'
        else:
            status = 'ok'
        doc: Dict[str, object] = {
            'status': status,
            'model_ready': model_ready,  # wire-ok: external health probes
            'loop_alive': loop_alive,  # wire-ok: external health probes
            'draining': self.draining.is_set(),
            'drained': self.drained.is_set(),  # wire-ok: external health probes
            'inflight': self.gen_inflight,  # wire-ok: external health probes
            # Stable key set: None until the engine can answer — probe
            # consumers must never key-miss on a starting replica.
            'kv': None,
        }
        # KV/radix summary for affinity-aware LB routing: kv_health()
        # is counters-only (this document is probed on a short
        # interval).  Guarded so plain engines without it stay probe-
        # compatible.
        kv_health = getattr(self.engine, 'kv_health', None)
        if model_ready and callable(kv_health):
            try:
                doc['kv'] = kv_health()
            except Exception:  # pylint: disable=broad-except
                pass   # health must never 500 over a stats race
        return doc

    _AUTO_PREFIX_MIN = 64        # shortest head worth caching
    _AUTO_PREFIX_TRACKED = 256   # tracked heads (simple size cap)

    def _maybe_auto_prefix(self, req: Request) -> None:
        """Count the request's bucket-quantized prompt head; on the
        second sighting, register it as a prefix (background thread) so
        later requests prefill suffix-only.  No-op unless auto_prefix
        and the engine has prefix slots."""
        if getattr(self.engine, '_radix', None) is not None:
            # Engine-level automatic radix caching supersedes this
            # whole-prompt heuristic: every completed prompt's full
            # blocks are already matchable at block granularity, so
            # counting heads here would only duplicate work.
            return
        if not self.auto_prefix or not self.engine.cfg.max_prefixes:
            return
        if req.want_prompt_logprobs:
            return                        # scoring bypasses prefix reuse
        n = len(req.tokens)
        starts = [b for b in self.engine.cfg.prefill_buckets
                  if self._AUTO_PREFIX_MIN <= b < n]
        if not starts:
            return
        b = starts[-1]                    # longest bucket inside the prompt
        key = (req.adapter, b, tuple(req.tokens[:b]))
        with self._auto_lock:
            if len(self._auto_counts) >= self._AUTO_PREFIX_TRACKED and \
                    key not in self._auto_counts:
                self._auto_counts.clear()     # cheap reset beats an LRU
            self._auto_counts[key] = self._auto_counts.get(key, 0) + 1
            hot = self._auto_counts[key] >= 2
            if (not hot or key in self._auto_inflight or
                    key in self._auto_failed):
                return
            if (req.adapter, key[2]) in self.engine._prefixes:
                return                      # already resident
            # Auto-registration only FILLS free prefix slots, never
            # evicts: with more hot heads than slots, registering an
            # evicted-but-hot key would evict another hot one — steady
            # state becomes one device prefill per request (LRU
            # thrash).  Explicit /cache_prefix keeps eviction rights.
            # Count IN-FLIGHT registrations as occupied (they land
            # later, in background threads — without the reservation
            # two concurrent registrations could overflow the registry
            # and trigger exactly the eviction this check forbids).
            if (len(self.engine._prefixes) + len(self._auto_inflight)
                    >= self.engine.cfg.max_prefixes):
                return
            self._auto_inflight.add(key)

        def register():
            ok = False
            try:
                self.engine.register_prefix(list(key[2]),
                                            adapter=req.adapter)
                ok = True
            except Exception:  # noqa: BLE001 — best-effort cache warm
                pass
            finally:
                with self._auto_lock:
                    self._auto_inflight.discard(key)
                    if not ok:
                        # A repeatably-failing capture must not burn a
                        # device forward per sighting.
                        self._auto_failed.add(key)

        threading.Thread(target=register, daemon=True).start()

    def submit(self, req: Request, timeout: float = 300.0,
               pre_admitted: bool = False,
               count_prefix: bool = True) -> Optional[RequestResult]:
        rid = req.request_id or uuid.uuid4().hex
        req.request_id = rid
        if req.arrival_time is None:   # TTFT counts slot-queue wait
            req.arrival_time = time.time()
        # Admission FIRST: a shed (429) request must neither count
        # toward head-hotness nor spawn device work mid-overload.
        # pre_admitted: the caller already holds this rid's admission
        # (the n>1 handler admits the whole batch atomically up front).
        if not pre_admitted:
            self._admit(rid)
        # count_prefix=False: an OpenAI `n` clone — the prompt head is
        # one HTTP request's, so hotness counts it once (choice 0),
        # else a single n>=2 request self-certifies as 'seen twice' and
        # burns a prefix slot + a capture forward on a one-off prompt.
        if count_prefix:
            self._maybe_auto_prefix(req)
        ev = threading.Event()
        self._events[rid] = ev
        self._queue.put(req)
        ev.wait(timeout)
        # Pop the event FIRST so a racing _deliver either stored the
        # result before this pop (we return it) or sees no event and
        # drops it (no leak).
        self._events.pop(rid, None)
        res = self._results.pop(rid, None)
        if res is not None and res.finish_reason not in ('error',
                                                         'cancelled'):
            self._note_first_token(rid, res.ttft_s)
        else:
            # Errors, timeouts AND cancels leave the backlog without
            # feeding the admission TTFT window — a cancelled result's
            # fabricated 0.0 TTFT would suppress shedding exactly when
            # cancels spike (overloaded clients giving up).
            self._drop_admitted(rid)
        if res is None:
            # Timed out: cancel INTO the engine so the slot and its
            # paged blocks free NOW — without this the abandoned
            # request keeps decoding to max_new_tokens for nobody
            # (submit_stream's finally has always done this; the
            # blocking path leaked).  A finish racing this cancel
            # delivers into _results with no event registered and is
            # dropped there; the stale pending mark then expires
            # (engine._CANCEL_MARK_TTL_S) or is cleared by the finish
            # itself when it won before the mark landed.
            self.engine.cancel(rid)
        return res

    def submit_stream(self, req: Request, timeout: float = 300.0,
                      pre_admitted: bool = False):
        """Submit and yield ('tokens', [ids]) chunks as they decode,
        terminated by ('done', RequestResult) — or ('timeout', None) if
        `timeout` passes with no new chunk.

        `timeout` is an INACTIVITY bound, not a total-duration bound: a
        generation still actively producing tokens is never cut off; the
        deadline resets on every received chunk.  (Queue depth under
        load shows up as time-to-first-chunk, which the same bound
        covers.)

        One queue carries both chunks and the terminal sentinel: the
        engine enqueues every chunk (under its lock) BEFORE _deliver
        runs, so ('done', res) is ordered after the last chunk — no
        polling, and the final event goes out the moment it exists.
        """
        rid = req.request_id or uuid.uuid4().hex
        req.request_id = rid
        if req.arrival_time is None:   # TTFT counts slot-queue wait
            req.arrival_time = time.time()
        if not pre_admitted:
            # NB: generator body — deferred to first next().  The HTTP
            # handler pre-admits instead, so the 429 can go out before
            # the SSE response line.
            self._admit(rid)
        self._maybe_auto_prefix(req)
        chunks: 'queue.Queue' = queue.Queue()
        req.stream_cb = lambda toks: chunks.put(('tokens', toks))
        self._stream_queues[rid] = chunks
        self._queue.put(req)
        finished = False
        try:
            while True:
                try:
                    item = chunks.get(timeout=timeout)
                except queue.Empty:
                    self._drop_admitted(rid)
                    yield ('timeout', None)
                    return
                if item[0] == 'tokens':
                    self._note_first_token(
                        rid, time.time() - req.arrival_time)
                elif item[0] == 'done':
                    # Prefill-only/error finishes never streamed a chunk.
                    finished = True
                    self._drop_admitted(rid)
                yield item
                if item[0] == 'done':
                    return
        finally:
            # The stream queue stays registered through the cancel
            # below: popping it first would route a racing natural
            # finish into _results (abandoned-drop) instead of the
            # chunks queue, making the finish invisible to the
            # stale-mark re-drain.
            if not finished:
                # Drain first: the generation may have finished
                # naturally with its 'done' sentinel unread (client
                # vanished at the end) — cancelling then would leave a
                # stale pending mark that could poison a retry reusing
                # the same client-supplied request_id.
                try:
                    while True:
                        if chunks.get_nowait()[0] == 'done':
                            finished = True
                except queue.Empty:
                    pass
            if not finished:
                # The consumer stopped early — client disconnected
                # mid-stream, stop string satisfied, or timeout.  Free
                # the decode slot NOW instead of generating to
                # max_new_tokens for nobody.
                if not self.engine.cancel(rid):
                    # Not in a slot: still queued (the mark drops it at
                    # dequeue — correct), OR it finished in the window
                    # between the drain above and cancel().  The engine
                    # delivers under its lock and cancel() takes that
                    # lock, so a finish that won the race has its
                    # 'done' sentinel enqueued by now: finding it means
                    # the pending mark is stale and must be cleared.
                    try:
                        while True:
                            if chunks.get_nowait()[0] == 'done':
                                self.engine.uncancel(rid)
                                break
                    except queue.Empty:
                        pass
            self._stream_queues.pop(rid, None)
            # Generator closed without a first token (client disconnect
            # before any chunk, GeneratorExit): the request leaves the
            # admission backlog — no-op when a first token already
            # removed it.
            self._drop_admitted(rid)


def _make_handler(server: InferenceServer):

    class Handler(BaseHTTPRequestHandler):

        def log_message(self, fmt, *args):  # quiet
            pass

        def _json(self, code: int, payload: dict,
                  extra_headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _shed(self, e: 'AdmissionError') -> None:
            """429 + Retry-After: wait long enough that the queue
            plausibly drains back under the bound."""
            import math
            retry_after = (e.retry_after_s if e.retry_after_s is not None
                           else max(1, math.ceil(e.projected_s -
                                                 e.bound_s)))
            self._json(429, {'error': str(e), 'shed': True,
                             'projected_ttft_s': round(e.projected_s, 2),
                             'bound_s': e.bound_s},
                       extra_headers={'Retry-After': str(retry_after)})

        def _sse_begin(self):
            """200 + SSE headers; returns the `data:`-line emitter
            (shared by the native and OpenAI streaming paths)."""
            self.send_response(200)
            self.send_header('Content-Type', 'text/event-stream')
            self.send_header('Cache-Control', 'no-cache')
            self.end_headers()

            def emit(payload: dict) -> None:
                self.wfile.write(
                    b'data: ' + json.dumps(payload).encode() + b'\n\n')
                self.wfile.flush()

            return emit

        def _stream(self, req: Request) -> None:
            """Server-sent events: one `data:` line per token chunk, a
            final `data:` with the full result, then connection close
            (no Content-Length — SSE semantics)."""
            emit = self._sse_begin()

            streamed: list = []
            prev_text = ''
            try:
                for kind, value in server.submit_stream(
                        req, pre_admitted=True):
                    if kind == 'tokens':
                        streamed.extend(value)
                        out = {'tokens': value}
                        if server.tokenizer is not None:
                            # Incremental detokenization: decode the FULL
                            # prefix and emit the suffix delta — chunk-
                            # local decoding breaks BPE merges and
                            # multi-byte characters at window boundaries.
                            text = server.tokenizer.decode(streamed)
                            out['text'] = text[len(prev_text):]
                            prev_text = text
                        emit(out)
                    elif kind == 'done':
                        final = {
                            'done': True,
                            'output_tokens': value.output_tokens,
                            'ttft_s': value.ttft_s,
                            'latency_s': value.latency_s,
                            'finish_reason': value.finish_reason,
                        }
                        if value.logprobs is not None:
                            final['logprobs'] = value.logprobs  # wire-ok: client-facing API field
                        if value.prompt_logprobs is not None:
                            final['prompt_logprobs'] = (  # wire-ok: client API
                                value.prompt_logprobs)
                        if value.error:
                            final['error'] = value.error
                        if value.error_class:
                            final['error_class'] = value.error_class  # wire-ok: client-facing API field
                        if server.tokenizer is not None:
                            final['text'] = server.tokenizer.decode(  # wire-ok: client-facing API field
                                value.output_tokens)
                        emit(final)
                    else:   # timeout — acknowledge what was streamed
                        emit({'done': True, 'error': 'timed out',  # wire-ok: client-facing API field
                              'finish_reason': 'error',  # wire-ok: client-facing API field
                              'output_tokens': streamed,  # wire-ok: client-facing API field
                              'ttft_s': 0.0, 'latency_s': 0.0})  # wire-ok: client-facing API field
            except (BrokenPipeError, ConnectionResetError):
                # Client went away mid-stream: closing the generator
                # runs submit_stream's finally, which cancels into the
                # engine — the slot and its paged blocks free now.
                pass

        def do_GET(self):
            if self.path in ('/health', '/'):
                if server.ready.is_set():
                    self._json(200, {'status': 'ok'})
                else:
                    self._json(503, {'status': 'starting'})
            elif self.path == '/healthz':
                # Readiness for the LB's active prober: 200 only while
                # the replica should receive traffic.  'starting',
                # 'draining' and 'dead' all answer 503 with the full
                # state document so the prober can tell them apart.
                doc = server.health()
                code = 200 if doc['status'] == 'ok' else 503
                headers = ({'X-SkyTpu-Draining': '1'}
                           if doc['draining'] else None)
                self._json(code, doc, extra_headers=headers)
            elif self.path == '/hot_prefixes':
                # Warm-failover export: the draining replica's hottest
                # radix prefixes, serialized topology-neutral (global
                # [L, Hkv, bs, D] rows, base64).  The LB fetches this
                # during drain and POSTs it to the survivor's
                # /adopt_blocks.
                export = getattr(server.engine, 'export_hot_prefixes',
                                 None)
                if not callable(export):
                    self._json(404, {'error': 'not found'})
                    return
                try:
                    self._json(200, export())
                except Exception as e:  # noqa: BLE001 — drain path:
                    # a failed export must degrade to cold failover,
                    # never crash the handler of a draining replica.
                    self._json(500, {'error': str(e)})
            elif self.path == '/v1/models':
                name = server.engine.model_config.name
                rows = [{'id': name, 'object': 'model', 'created': 0,
                         'owned_by': 'skypilot_tpu'}]
                rows += [{'id': a, 'object': 'model', 'created': 0,
                          'owned_by': 'skypilot_tpu', 'parent': name}
                         for a in sorted(server.engine.adapters)]
                self._json(200, {'object': 'list', 'data': rows})
            elif self.path == '/stats':
                eng = server.engine
                st = eng.stats()
                self._json(200, {
                    'slots_active': sum(s is not None  # wire-ok: operator metrics surface
                                        for s in eng._slots),
                    'num_slots': eng.cfg.num_slots,
                    'queue_depth': server._queue.qsize(),  # wire-ok: operator metrics surface
                    'awaiting_first_token': len(server._awaiting_first),  # wire-ok: operator metrics surface
                    'shed_count': server.shed_count,  # wire-ok: operator metrics surface
                    'draining': server.draining.is_set(),  # wire-ok: operator metrics surface
                    'gen_inflight': server.gen_inflight,  # wire-ok: operator metrics surface
                    'drain_refused': server.drain_refused,  # wire-ok: operator metrics surface
                    'spec': dict(eng.spec_stats),
                    # THE structured KV section: layout, blocks, bytes,
                    # prefix + radix caching (hits/hit_rate/
                    # tokens_reused/nodes/blocks_held/evictions),
                    # admission — engine.stats()['kv'].
                    'kv': st['kv'],  # wire-ok: operator metrics surface
                    # Deprecated aliases of kv.* (old dashboards):
                    'prefix': dict(eng.prefix_stats),  # wire-ok: operator metrics surface
                    'resident_prefixes': len(eng._prefixes),  # wire-ok: operator metrics surface
                    'kv_cache': st,  # wire-ok: operator metrics surface
                    'adapters': sorted(eng.adapters),  # wire-ok: operator metrics surface
                    'prefill_chunk': eng.cfg.prefill_chunk,  # wire-ok: operator metrics surface
                    'chunking_slots': len(eng._chunking),  # wire-ok: operator metrics surface
                    'chunk': dict(eng.chunk_stats),  # wire-ok: operator metrics surface
                    # Failure/recovery counters (engine.fault_stats):
                    # internal_errors, deadline_evictions, loop_restarts,
                    # quarantined_batches, nonfinite_lanes.
                    'faults': dict(eng.fault_stats),  # wire-ok: operator metrics surface
                    # QoS plane (engine.stats()['qos']): scheduler
                    # depths per class, preemptions, sheds, per-tenant
                    # admitted/shed.
                    'qos': st.get('qos'),  # wire-ok: operator metrics surface
                })
            else:
                self._json(404, {'error': 'not found'})

        # ----------------------------------------- OpenAI-compatible API

        def _openai_request(self, payload, chat: bool):
            """Parse a /v1/* body into (Request, stop, opts) or answer
            the error and return None.  opts: logprobs (bool), echo
            (bool), zero_max (max_tokens=0 — the lm-eval-harness
            loglikelihood pattern: score the prompt, generate
            nothing)."""
            try:
                max_new = payload.get('max_tokens', 16)
                max_new = None if max_new is None else int(max_new)
                # OpenAI/vLLM default: sample at temperature 1.0.  A
                # client wanting greedy must ask for it — defaulting to
                # 0.0 silently made every temperature-less request
                # greedy (r3 advisor).  (The native /generate API keeps
                # its documented greedy default.)
                temperature = float(payload.get('temperature', 1.0))
                stop = payload.get('stop') or []
                if isinstance(stop, str):
                    stop = [stop]
                stop = [str(s) for s in stop]
                # Completions `logprobs` is an int (alternatives per
                # position, OpenAI caps it at 5); tolerate bool True as
                # 1.  None/False/absent = no logprobs.  Chat uses
                # `logprobs: true` + `top_logprobs: 0..k` instead.
                lp_raw = payload.get('logprobs')
                if chat:
                    if payload.get('top_logprobs') is not None and \
                            not lp_raw:
                        # OpenAI rejects this combination loudly.
                        self._json(400, {'error': {
                            'message': 'top_logprobs requires logprobs '
                                       'to be true',
                            'type': 'invalid_request_error'}})
                        return None
                    lp_k = (int(payload.get('top_logprobs', 0))
                            if lp_raw else None)
                elif lp_raw is None or lp_raw is False:
                    lp_k = None
                elif lp_raw is True:
                    lp_k = 1
                else:
                    lp_k = int(lp_raw)
                echo = bool(payload.get('echo'))
                n_raw = payload.get('n')
                n_choices = 1 if n_raw is None else int(n_raw)
                # Extension field (no OpenAI equivalent): server-side
                # deadline; the engine evicts past it
                # (finish_reason='deadline').
                deadline_raw = payload.get('deadline_s')
                deadline_s = (None if deadline_raw is None
                              else float(deadline_raw))
                # Extension fields: QoS class + fair-queueing tenant
                # key (engine WFQ; the LB also rate-limits on tenant).
                priority_raw = payload.get('priority')
                priority = (None if priority_raw is None
                            else str(priority_raw))
                tenant_raw = payload.get('tenant_id')
                tenant_id = (None if tenant_raw is None
                             else str(tenant_raw))
            except (TypeError, ValueError) as e:
                self._json(400, {'error': {'message': f'bad field: {e}',
                                           'type': 'invalid_request_error'}})
                return None
            if deadline_s is not None and deadline_s <= 0:
                self._json(400, {'error': {
                    'message': 'deadline_s must be > 0',
                    'type': 'invalid_request_error'}})
                return None
            if priority is not None and \
                    priority not in qos_mod.PRIORITY_CLASSES:
                self._json(400, {'error': {
                    'message': (
                        f'unknown priority {priority!r}; expected one '
                        f'of {list(qos_mod.PRIORITY_CLASSES)}'),
                    'type': 'invalid_request_error'}})
                return None
            max_n = max(1, min(8, server.engine.cfg.num_slots))
            if not 1 <= n_choices <= max_n:
                self._json(400, {'error': {
                    'message': f'n must be between 1 and {max_n}',
                    'type': 'invalid_request_error'}})
                return None
            if n_choices > 1 and payload.get('stream'):
                self._json(400, {'error': {
                    'message': 'n > 1 is not supported with stream',
                    'type': 'invalid_request_error'}})
                return None
            want_lp = lp_k is not None
            max_k = min(5, server.engine.cfg.logprob_topk)
            if want_lp and not 0 <= lp_k <= max_k:
                # Never silently return fewer alternatives than asked
                # (r3: k>1 requests got k=1 without an error).
                field = 'top_logprobs' if chat else 'logprobs'
                self._json(400, {'error': {
                    'message': f'{field} must be between 0 and {max_k}',
                    'type': 'invalid_request_error'}})
                return None
            opts = {'logprobs': want_lp, 'logprob_k': lp_k or 0,
                    'echo': echo, 'zero_max': max_new == 0,
                    'n': n_choices}
            if opts['zero_max']:
                # The engine always produces the prefill token; trim it
                # from the response instead of rejecting the request.
                max_new = 1
            if chat and echo:
                self._json(400, {'error': {
                    'message': 'echo is supported on /v1/completions '
                               'only',
                    'type': 'invalid_request_error'}})
                return None
            if payload.get('stream') and (want_lp or echo or
                                          opts['zero_max']):
                # Reject loudly instead of silently diverging from
                # OpenAI semantics on the streaming path.
                self._json(400, {'error': {
                    'message': 'logprobs/echo/max_tokens=0 are not '
                               'supported with stream',
                    'type': 'invalid_request_error'}})
                return None
            if chat:
                messages = payload.get('messages')
                if (not isinstance(messages, list) or not messages or
                        not all(isinstance(m, dict) for m in messages)):
                    self._json(400, {'error': {
                        'message': '"messages" must be a non-empty list '
                                   'of {role, content} objects',
                        'type': 'invalid_request_error'}})
                    return None
                if server.tokenizer is None:
                    self._json(400, {'error': {
                        'message': 'chat API needs a tokenizer '
                                   '(--tokenizer / --hf-model)',
                        'type': 'invalid_request_error'}})
                    return None
                try:
                    tokens = server.tokenizer.apply_chat_template(
                        messages, tokenize=True,
                        add_generation_prompt=True)
                except Exception:  # noqa: BLE001 — no template in ckpt
                    text = ''.join(
                        f"{m.get('role', 'user')}: {m.get('content', '')}\n"
                        for m in messages) + 'assistant: '
                    tokens = server.tokenizer.encode(text)
            else:
                prompt = payload.get('prompt')
                if isinstance(prompt, list) and all(
                        isinstance(t, int) for t in prompt):
                    tokens = prompt        # OpenAI token-array form
                elif isinstance(prompt, str):
                    if server.tokenizer is None:
                        self._json(400, {'error': {
                            'message': 'string prompts need a tokenizer '
                                       '(--tokenizer / --hf-model); pass '
                                       'a token array instead',
                            'type': 'invalid_request_error'}})
                        return None
                    tokens = server.tokenizer.encode(prompt)
                else:
                    self._json(400, {'error': {
                        'message': '"prompt" (string or token array) '
                                   'required',
                        'type': 'invalid_request_error'}})
                    return None
                if not tokens:
                    self._json(400, {'error': {
                        'message': 'empty prompt',
                        'type': 'invalid_request_error'}})
                    return None
            # Adapter selection, LoRAX-style: the OpenAI "model" field
            # naming a registered adapter selects it (an "adapter"
            # field works too); the base model id or absence = base.
            # An unknown model value is a 404 (vLLM-compatible), never
            # a silent base-model response.
            adapter = payload.get('adapter')
            model_field = payload.get('model')
            if adapter is None and model_field:
                if model_field in server.engine.adapters:
                    adapter = model_field
                elif model_field != server.engine.model_config.name:
                    self._json(404, {'error': {
                        'message': f'model {model_field!r} not found '
                                   '(served: '
                                   f'{server.engine.model_config.name}'
                                   ' + adapters '
                                   f'{sorted(server.engine.adapters)})',
                        'type': 'invalid_request_error',
                        'code': 'model_not_found'}})
                    return None
            req = Request(tokens=[int(t) for t in tokens],
                          max_new_tokens=max_new,
                          temperature=temperature,
                          request_id=uuid.uuid4().hex,
                          adapter=adapter,
                          want_prompt_logprobs=want_lp and echo,
                          deadline_s=deadline_s,
                          priority=priority,
                          tenant_id=tenant_id)
            return req, stop, opts

        @staticmethod
        def _openai_finish(reason: str) -> str:
            return {'eos': 'stop', 'length': 'length'}.get(reason, reason)

        def _openai_generate(self, payload, chat: bool) -> None:
            parsed = self._openai_request(payload, chat)
            if parsed is None:
                return
            req, stop, opts = parsed
            kind = 'chat.completion' if chat else 'text_completion'
            rid = ('chatcmpl-' if chat else 'cmpl-') + req.request_id[:24]
            # Echo the model that actually serves the request (the
            # adapter name when one is selected).
            model_name = req.adapter or server.engine.model_config.name
            if payload.get('stream'):
                try:
                    server._admit(req.request_id)
                except AdmissionError as e:
                    self._shed(e)
                    return
                try:
                    self._openai_stream(req, stop, chat, rid, model_name)
                finally:
                    server._drop_admitted(req.request_id)
                return
            # n > 1 (OpenAI `n`): independent engine requests batched
            # by continuous batching like any concurrent traffic; each
            # samples its own tokens (identical under temperature 0).
            # dataclasses.replace copies EVERY field, so future
            # sampling knobs cannot be silently dropped from clones;
            # prompt scoring runs once (clones reuse choice 0's scores
            # — the prompt is identical).
            import dataclasses as _dc
            reqs = [req] + [
                _dc.replace(req, tokens=list(req.tokens),
                            request_id=uuid.uuid4().hex,
                            arrival_time=None, stream_cb=None,
                            want_prompt_logprobs=False)
                for _ in range(opts['n'] - 1)
            ]
            if len(reqs) > 1:
                # Admit the whole batch ATOMICALLY up front: a partial
                # shed must 429 immediately (with a fresh Retry-After)
                # and waste no device work, not join n-1 generations.
                admitted = []
                try:
                    for r in reqs:
                        server._admit(r.request_id)
                        admitted.append(r.request_id)
                except AdmissionError as e:
                    for a in admitted:
                        server._drop_admitted(a)
                    self._shed(e)
                    return
            results: list = [None] * len(reqs)

            def one(i):
                try:
                    results[i] = server.submit(
                        reqs[i], pre_admitted=len(reqs) > 1,
                        count_prefix=i == 0)
                except AdmissionError as e:
                    # Only reachable for n == 1 (batch pre-admits).
                    results[i] = ('shed', e)

            if len(reqs) == 1:
                one(0)
                if isinstance(results[0], tuple):
                    self._shed(results[0][1])
                    return
            else:
                threads = [threading.Thread(target=one, args=(i,),
                                            daemon=True)
                           for i in range(len(reqs))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            if any(r is None for r in results):
                self._json(504, {'error': {'message': 'timed out',
                                           'type': 'timeout'}})
                return
            err = next((r for r in results
                        if r.finish_reason == 'error'), None)
            if err is not None:
                code = 500 if err.error_class == 'internal' else 400
                self._json(code, {'error': {
                    'message': err.error or 'bad request',
                    'type': 'invalid_request_error'
                    if code == 400 else 'internal_error'}})
                return
            res = results[0]
            # Clones skipped prompt scoring (identical prompt): reuse
            # choice 0's scores so echo+logprobs choices 1..n-1 carry
            # them too.
            for r in results[1:]:
                r.prompt_logprobs = res.prompt_logprobs
                r.prompt_top_logprobs = res.prompt_top_logprobs
            choices = []
            completion_tokens = 0
            for index, res_i in enumerate(results):
                choice, n_completion = self._openai_choice(
                    res_i, opts, stop, chat, index)
                choices.append(choice)
                completion_tokens += n_completion
            usage = {'prompt_tokens': len(res.prompt_tokens),
                     'completion_tokens': completion_tokens,
                     'total_tokens': len(res.prompt_tokens) +
                     completion_tokens}
            self._json(200, {'id': rid, 'object': kind,
                             'created': int(time.time()),
                             'model': model_name,
                             'choices': choices, 'usage': usage})

        def _openai_choice(self, res, opts, stop, chat, index):
            """One result -> one OpenAI choice object; returns
            (choice, completion_tokens_after_stop_truncation)."""
            finish = self._openai_finish(res.finish_reason)
            out_tokens = list(res.output_tokens)
            out_lps = list(res.logprobs or [])
            if opts['zero_max']:
                # max_tokens=0: the engine generated one token for the
                # prefill; the client asked for none.
                out_tokens, out_lps, finish = [], [], 'length'
            text = None
            n_completion = len(out_tokens)
            if server.tokenizer is not None:
                text = server.tokenizer.decode(out_tokens)
                at = self._find_stop(text, stop)
                if at >= 0:
                    text, finish = text[:at], 'stop'
                    # Usage counts only tokens up to the truncation
                    # (vLLM-consistent): smallest token prefix whose
                    # decode covers the kept text.
                    for i in range(len(out_tokens) + 1):
                        if len(server.tokenizer.decode(
                                out_tokens[:i])) >= at:
                            n_completion = i
                            break
            if chat:
                choice = {'index': index, 'finish_reason': finish,
                          'logprobs': None,
                          'message': {'role': 'assistant',
                                      'content': text or ''}}
                if opts['logprobs']:
                    # Chat logprobs shape (OpenAI): content = one entry
                    # per generated token with its logprob + the
                    # requested top_logprobs alternatives.  Chat always
                    # has a tokenizer (enforced at parse).
                    tok = server.tokenizer
                    k = opts['logprob_k']

                    def entry(tid, lp_val):
                        s_ = tok.decode([tid])
                        return {'token': s_, 'logprob': lp_val,
                                'bytes': list(s_.encode('utf-8'))}

                    content = []
                    tops_all = list(res.top_logprobs or [])
                    for i in range(n_completion):
                        e = entry(out_tokens[i], out_lps[i])
                        e['top_logprobs'] = [
                            entry(tid, lp_val)
                            for tid, lp_val in tops_all[i][:k]
                        ] if i < len(tops_all) else []
                        content.append(e)
                    choice['logprobs'] = {'content': content}
            else:
                if opts['echo'] and text is not None:
                    text = server.tokenizer.decode(
                        res.prompt_tokens) + text
                choice = {'index': index, 'finish_reason': finish,
                          'text': text if text is not None
                          else '', 'logprobs': None}
                if text is None:    # token-only serving
                    choice['tokens'] = out_tokens
                if opts['logprobs']:
                    ids = (list(res.prompt_tokens) if opts['echo']
                           else []) + out_tokens[:n_completion]
                    lps = ((list(res.prompt_logprobs or [])
                            if opts['echo'] else []) +
                           out_lps[:n_completion])
                    tops = ((list(res.prompt_top_logprobs or [])
                             if opts['echo'] else []) +
                            list(res.top_logprobs
                                 or [])[:n_completion])
                    tok = server.tokenizer

                    def tstr(t):
                        return tok.decode([t]) if tok else str(t)

                    strs = [tstr(t) for t in ids]
                    offsets, pos = [], 0
                    for s_ in strs:
                        offsets.append(pos)
                        pos += len(s_)
                    # The requested k alternatives per position (the
                    # engine computes logprob_topk, best first; entry 0
                    # of an echo is null like its token_logprob).
                    # k=0: OpenAI still returns the dicts, holding only
                    # positions' chosen-token entries via token_logprobs
                    # — we emit empty dicts, matching vLLM.
                    k = opts['logprob_k']
                    choice['logprobs'] = {
                        'tokens': strs,
                        'token_logprobs': lps,
                        'top_logprobs': [
                            None if t is None else
                            {tstr(i): l for i, l in t[:k]}
                            for t in tops
                        ],
                        'text_offset': offsets,
                    }
            return choice, n_completion

        @staticmethod
        def _find_stop(text: str, stop) -> int:
            """Earliest stop-string position in text, or -1."""
            hit = -1
            for s in stop:
                at = text.find(s)
                if at >= 0 and (hit < 0 or at < hit):
                    hit = at
            return hit

        def _openai_stream(self, req, stop, chat, rid, model_name) -> None:
            """OpenAI-style SSE: one chunk object per decode window,
            a finish chunk, then `data: [DONE]`."""
            emit = self._sse_begin()
            kind = ('chat.completion.chunk' if chat
                    else 'text_completion')
            created = int(time.time())

            def emit_done() -> None:
                self.wfile.write(b'data: [DONE]\n\n')   # literal, no JSON
                self.wfile.flush()

            def chunk(delta_text, finish=None, first=False, tokens=None):
                if chat:
                    delta = {}
                    if first:
                        delta['role'] = 'assistant'
                    if delta_text:
                        delta['content'] = delta_text
                    choice = {'index': 0, 'delta': delta,
                              'finish_reason': finish}
                else:
                    choice = {'index': 0, 'text': delta_text,
                              'finish_reason': finish}
                    if tokens is not None:   # token-only serving
                        choice['tokens'] = tokens
                return {'id': rid, 'object': kind, 'created': created,
                        'model': model_name, 'choices': [choice]}

            streamed: list = []
            emitted = 0          # chars of decoded text already sent
            # A stop string can straddle decode windows: hold back the
            # longest possible stop-prefix so an already-emitted chunk
            # never contains part of a match (stream == non-stream).
            hold = max((len(s) for s in stop), default=1) - 1
            first = True
            try:
                for item_kind, value in server.submit_stream(
                        req, pre_admitted=True):
                    if item_kind == 'tokens':
                        streamed.extend(value)
                        if server.tokenizer is None:
                            # Token-only serving: the ids ARE the data.
                            emit(chunk('', tokens=value, first=first))
                            first = False
                            continue
                        # Full-prefix decode, emit the suffix delta
                        # (chunk-local decoding breaks BPE merges).
                        text = server.tokenizer.decode(streamed)
                        hit = self._find_stop(text, stop)
                        if hit >= 0:
                            # Truncate at the stop string; returning
                            # closes the generator, which CANCELS the
                            # engine request — the decode slot frees
                            # immediately (same contract as a client
                            # disconnect).
                            delta = text[:hit][emitted:]
                            if delta:
                                emit(chunk(delta, first=first))
                            emit(chunk('', finish='stop'))
                            emit_done()
                            return
                        safe = max(emitted, len(text) - hold)
                        delta = text[emitted:safe]
                        if delta:
                            emit(chunk(delta, first=first))
                            first = False
                            emitted = safe
                    elif item_kind == 'done':
                        finish = ('error' if value.finish_reason ==
                                  'error' else self._openai_finish(
                                      value.finish_reason))
                        if (server.tokenizer is not None and
                                value.finish_reason != 'error'):
                            # Flush the held-back tail (stop-checked).
                            text = server.tokenizer.decode(
                                value.output_tokens)
                            hit = self._find_stop(text, stop)
                            if hit >= 0:
                                text, finish = text[:hit], 'stop'
                            delta = text[emitted:]
                            if delta:
                                emit(chunk(delta, first=first))
                                first = False
                        emit(chunk('', finish=finish))
                        emit_done()
                    else:   # timeout
                        emit(chunk('', finish='error'))
                        emit_done()
            except (BrokenPipeError, ConnectionResetError):
                pass

        _GENERATE_PATHS = ('/generate', '/generate_text',
                           '/v1/completions', '/v1/chat/completions')

        def do_POST(self):
            try:
                n = int(self.headers.get('Content-Length', 0))
                payload = json.loads(self.rfile.read(n) or b'{}')
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {'error': str(e)})
                return
            if self.path == '/drain':
                # Graceful drain: stop admitting, finish in-flight up
                # to deadline_s, advertise via /healthz.  cancel=true
                # reverses an in-progress drain (tests/operators).
                if payload.get('cancel'):
                    server.undrain()
                    self._json(200, server.health())
                    return
                try:
                    deadline = payload.get('deadline_s')
                    deadline = (None if deadline is None
                                else float(deadline))
                except (TypeError, ValueError) as e:
                    self._json(400, {'error': f'bad field: {e}'})
                    return
                if deadline is not None and deadline <= 0:
                    self._json(400, {'error': 'deadline_s must be > 0'})
                    return
                server.drain(deadline)
                self._json(200, server.health())
                return
            if self.path in self._GENERATE_PATHS:
                # Drain gate: a draining replica admits nothing new.
                # The 503 carries Retry-After + X-SkyTpu-Draining so
                # the LB treats it as retry-elsewhere, never a failure.
                if not server.begin_generate():
                    self._json(503, {'error': 'replica draining',
                                     'draining': True},
                               extra_headers={'Retry-After': '1',
                                              'X-SkyTpu-Draining': '1'})
                    return
                try:
                    if self.path == '/v1/completions':
                        self._openai_generate(payload, chat=False)
                    elif self.path == '/v1/chat/completions':
                        self._openai_generate(payload, chat=True)
                    else:
                        self._native_generate(payload)
                finally:
                    server.end_generate()
                return
            if self.path == '/load_adapter':
                # Multi-LoRA: load a trained adapter artifact (.npz from
                # train.lora.save_adapter_npz) into a stack slot; later
                # requests select it by name ("adapter" field, or the
                # OpenAI "model" field).
                name = payload.get('name')
                path = payload.get('path')
                if not name or not path:
                    self._json(400, {'error': '"name" and "path" '
                                     'required'})
                    return
                # The API is unauthenticated: an arbitrary path here
                # would let any network client load or probe files on
                # the host (error text reveals existence).  Confine to
                # the operator-chosen --adapter-dir; off by default.
                if server.adapter_dir is None:
                    self._json(403, {'error':
                                     'runtime adapter loading disabled; '
                                     'start the server with '
                                     '--adapter-dir to enable'})
                    return
                root = os.path.realpath(server.adapter_dir)
                resolved = os.path.realpath(
                    os.path.join(root, str(path)))
                if not (resolved == root or
                        resolved.startswith(root + os.sep)):
                    self._json(400, {'error': 'adapter path escapes '
                                     '--adapter-dir'})
                    return
                from skypilot_tpu.train.lora import load_adapter_npz
                try:
                    tree = load_adapter_npz(resolved)
                    idx = server.engine.register_adapter(name, tree)
                except Exception as e:  # noqa: BLE001 — everything here
                    # is client-input-driven (missing file, a directory,
                    # corrupt npz, wrong family/rank): a bad artifact
                    # must be a JSON 400, never a crashed handler thread.
                    self._json(400, {'error': str(e)})
                    return
                self._json(200, {'adapter': name, 'slot': idx})
                return
            if self.path == '/adopt_blocks':
                # Warm-failover import: adopt another replica's
                # serialized hot prefixes into this engine's radix
                # tree (LB-orchestrated during drain).  Mismatched
                # model/geometry/dtype is a clean 400 — the survivor
                # then just serves cold.
                adopt = getattr(server.engine, 'adopt_prefixes', None)
                if not callable(adopt):
                    self._json(404, {'error': 'not found'})
                    return
                try:
                    self._json(200, adopt(payload))
                except (TypeError, ValueError, KeyError) as e:
                    self._json(400, {'error': str(e)})
                except Exception as e:  # noqa: BLE001 — adoption is an
                    # optimization; a failure must leave the survivor
                    # serving (cold), not crash its handler thread.
                    self._json(500, {'error': str(e)})
                return
            if self.path == '/cache_prefix':
                # Register a prefix (system prompt): its KV rows stay
                # on device and matching prompts prefill suffix-only.
                # Under --auto-prefix-cache this is OPTIONAL PINNING:
                # caching already happens automatically, and this call
                # just marks the prefix's radix nodes eviction-exempt
                # (cached_prefix_len is then block-aligned).
                tokens = payload.get('tokens')
                if tokens is None and server.tokenizer is not None:
                    prompt = payload.get('prompt')
                    if prompt:
                        tokens = server.tokenizer.encode(prompt)
                if not isinstance(tokens, list) or not tokens:
                    self._json(400, {'error': '"tokens" list (or '
                                     '"prompt" with a tokenizer) '
                                     'required'})
                    return
                try:
                    n = server.engine.register_prefix(
                        [int(t) for t in tokens],
                        adapter=payload.get('adapter'))
                except (TypeError, ValueError) as e:
                    self._json(400, {'error': str(e)})
                    return
                self._json(200, {'cached_prefix_len': n})
                return
            self._json(404, {'error': 'not found'})

        def _native_generate(self, payload: dict) -> None:
            if self.path == '/generate':
                tokens = payload.get('tokens')
                if not isinstance(tokens, list) or not tokens:
                    self._json(400, {'error': '"tokens" list required'})
                    return
            else:   # /generate_text
                if server.tokenizer is None:
                    self._json(400, {'error': 'no tokenizer configured'})
                    return
                tokens = server.tokenizer.encode(payload.get('prompt', ''))
                if not tokens:
                    self._json(400, {'error': 'empty prompt'})
                    return
            # Validate types HERE: a malformed field must become a 400,
            # never an exception inside the engine thread.
            try:
                tokens = [int(t) for t in tokens]
                max_new = payload.get('max_new_tokens')
                max_new = None if max_new is None else int(max_new)
                temperature = float(payload.get('temperature', 0.0))
                deadline = payload.get('deadline_s')
                deadline = None if deadline is None else float(deadline)
                priority = payload.get('priority')
                priority = None if priority is None else str(priority)
                tenant_id = payload.get('tenant_id')
                tenant_id = None if tenant_id is None else str(tenant_id)
            except (TypeError, ValueError) as e:
                self._json(400, {'error': f'bad field: {e}'})
                return
            if deadline is not None and deadline <= 0:
                self._json(400, {'error': 'deadline_s must be > 0'})
                return
            if priority is not None and \
                    priority not in qos_mod.PRIORITY_CLASSES:
                self._json(400, {'error': (
                    f'unknown priority {priority!r}; expected one of '
                    f'{list(qos_mod.PRIORITY_CLASSES)}')})
                return
            req = Request(tokens=tokens, max_new_tokens=max_new,
                          temperature=temperature,
                          request_id=uuid.uuid4().hex,
                          adapter=payload.get('adapter'),
                          want_prompt_logprobs=bool(
                              payload.get('prompt_logprobs')),
                          deadline_s=deadline,
                          priority=priority,
                          tenant_id=tenant_id)
            if payload.get('stream'):
                # Admit BEFORE the SSE 200 goes out: a shed must be a
                # clean 429 the client (and LB) can act on.
                try:
                    server._admit(req.request_id)
                except AdmissionError as e:
                    self._shed(e)
                    return
                try:
                    self._stream(req)
                finally:
                    # Pre-admitted rid must not leak if _stream died
                    # before the generator ran (e.g. BrokenPipeError on
                    # the SSE headers) — idempotent on success paths.
                    server._drop_admitted(req.request_id)
                return
            try:
                res = server.submit(req)
            except AdmissionError as e:
                self._shed(e)
                return
            if res is None:
                self._json(504, {'error': 'timed out'})
                return
            if res.finish_reason == 'error':
                code = 500 if res.error_class == 'internal' else 400
                self._json(code, {'error': res.error or 'bad request'})
                return
            out = {
                'output_tokens': res.output_tokens,
                'ttft_s': res.ttft_s,
                'latency_s': res.latency_s,
                'finish_reason': res.finish_reason,
            }
            # Typed non-error terminals (deadline shed / cancel) carry
            # their reason through — a client must be able to tell a
            # QoS shed from having generated zero tokens.
            if res.error:
                out['error'] = res.error
            if res.error_class:
                out['error_class'] = res.error_class
            if payload.get('logprobs'):
                out['logprobs'] = res.logprobs
            if payload.get('prompt_logprobs'):
                out['prompt_logprobs'] = res.prompt_logprobs
            if server.tokenizer is not None:
                out['text'] = server.tokenizer.decode(res.output_tokens)
            self._json(200, out)

    return Handler


class _BurstTolerantHTTPServer(ThreadingHTTPServer):
    # Default listen backlog (5) RSTs connections during an arrival
    # burst BEFORE admission control can answer 429 — the shed path
    # must see the request to shed it.
    request_queue_size = 128


def serve(engine: InferenceEngine, host: str = '0.0.0.0', port: int = 8100,
          tokenizer: Optional[object] = None,
          max_projected_ttft_s: Optional[float] = None,
          max_queue: Optional[int] = None,
          adapter_dir: Optional[str] = None,
          auto_prefix: bool = False,
          warmup: bool = False) -> None:
    srv = InferenceServer(engine, tokenizer,
                          max_projected_ttft_s=max_projected_ttft_s,
                          max_queue=max_queue, adapter_dir=adapter_dir,
                          auto_prefix=auto_prefix, warmup=warmup)
    srv.start()
    httpd = _BurstTolerantHTTPServer((host, port), _make_handler(srv))
    # Graceful drain exit: once a drain (POST /drain or SIGTERM)
    # finishes its in-flight work, shut the listener down and return —
    # the process exits cleanly.  (_fire_on_drained already runs the
    # callback off-thread, so shutdown() cannot deadlock against the
    # handler thread that delivered the last completion.)
    srv._on_drained = httpd.shutdown

    def _sigterm(signum, frame):  # pylint: disable=unused-argument
        # Preemption notice: stop admitting (503 + Retry-After), finish
        # in-flight up to the drain timeout, then exit.  The env knob
        # (not serve.constants) is the contract here: the replica plane
        # must not import the control plane (skycheck LAYER001), and
        # SKYTPU_SERVE_DRAIN_TIMEOUT is what the controller exports.
        srv.drain(float(os.environ.get('SKYTPU_SERVE_DRAIN_TIMEOUT',
                                       60.0)))

    import signal
    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass   # not the main thread (embedded/test use): no signal hook
    try:
        httpd.serve_forever()
    finally:
        srv.stop()


def parse_tenant_weights(
        spec: Optional[str]) -> Optional[Dict[str, float]]:
    """'tenantA=3,tenantB=1.5' -> {'tenantA': 3.0, 'tenantB': 1.5}.
    Shared by --qos-tenant-weights here and `skytpu infer serve`."""
    if not spec:
        return None
    out: Dict[str, float] = {}
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        if '=' not in part:
            raise ValueError(
                f'bad tenant weight {part!r} (want tenant=weight)')
        tenant, w = part.split('=', 1)
        out[tenant.strip()] = float(w)
    return out or None


def run(model: str = 'llama-1b', host: str = '0.0.0.0', port: int = 8100,
        num_slots: int = 8, max_cache_len: int = 2048,
        tokenizer_name: Optional[str] = None,
        eos_id: Optional[int] = None,
        decode_steps: int = 8,
        hf_model: Optional[str] = None,
        cache_dtype: str = 'bfloat16',
        tensor_parallel: int = 0,
        weight_dtype: str = 'bf16',
        prefills_per_gap: int = 4,
        platform: Optional[str] = None,
        max_ttft: Optional[float] = None,
        max_queue: Optional[int] = None,
        draft_len: int = 0,
        ngram_max: int = 4,
        max_prefixes: int = 16,
        lora_rank: int = 0,
        lora_max_adapters: int = 8,
        adapter_dir: Optional[str] = None,
        adaptive_window: bool = False,
        decode_lookahead: bool = False,
        auto_prefix: bool = False,
        prefill_chunk: int = 0,
        kv_block_size: int = 0,
        kv_blocks: Optional[int] = None,
        auto_prefix_cache: bool = False,
        host_kv_bytes: int = 0,
        qos: bool = False,
        qos_tenant_weights: Optional[str] = None,
        warmup: bool = False) -> None:
    """Build engine (+ optional tokenizer) and serve.  Shared by the
    module entry point and the `skytpu infer serve` CLI.

    platform: pin jax onto 'cpu'/'tpu' (None = whatever jax picks).
    The config update AFTER importing jax is the only reliable pin on
    hosts whose site hooks rewrite JAX_PLATFORMS at import time; CPU
    replicas (dev serving, hermetic CI) need it.

    hf_model: HuggingFace Llama checkpoint (local path or warm cache) —
    real pretrained weights instead of the registry's random init.  The
    tokenizer defaults to the same checkpoint.

    tensor_parallel: shard the model over this many local chips (a
    'tensor' mesh axis); 0/1 = single-chip.  Requires num_kv_heads
    divisible by the degree.

    weight_dtype: 'int8' stores decoder projections quantized
    (per-channel scales) — half the weight HBM, faster decode; a 7B
    fits one 16 GB v5e chip.  Llama-family only.
    """
    import dataclasses

    if platform:
        import jax
        jax.config.update('jax_platforms', platform)

    import jax.numpy as jnp

    if tensor_parallel and tensor_parallel > 1:
        # Validate BEFORE the (potentially tens-of-GB) weight load below
        # — a flag typo must fail in milliseconds.
        import jax
        n_local = len(jax.devices())
        if tensor_parallel > n_local:
            raise ValueError(
                f'--tensor-parallel {tensor_parallel} exceeds the '
                f'{n_local} visible device(s); a mesh needs one chip '
                'per shard')

    params = None
    tokenizer_implied = False   # tokenizer_name defaulted from hf_model
    if hf_model:
        import jax
        import transformers

        from skypilot_tpu.models import hf_import
        # Family check from config.json alone — fail in milliseconds,
        # before the (potentially tens-of-GB) weight load.
        mt = getattr(transformers.AutoConfig.from_pretrained(hf_model),
                     'model_type', None)
        if mt not in ('llama', 'qwen2', 'mistral', 'mixtral', 'gpt2',
                      'gemma'):
            raise ValueError(
                f'--hf-model must be a supported causal-LM checkpoint '
                f"(model_type 'llama', 'qwen2', 'mistral', 'mixtral', "
                f"'gpt2' or 'gemma'); got model_type={mt!r}")
        # Serving: bf16 weights end to end (half the host RAM and HBM,
        # MXU-native).
        model_config, tree = hf_import.load_hf_model(
            hf_model, param_dtype=jnp.bfloat16)
        if weight_dtype == 'int8':
            from skypilot_tpu.models.llama import LlamaConfig
            from skypilot_tpu.models.quantize import quantize_params
            if not isinstance(model_config, LlamaConfig):
                raise ValueError(
                    '--weight-dtype int8 currently supports the llama '
                    f'family; got {type(model_config).__name__}')
            model_config = dataclasses.replace(model_config,
                                               weight_dtype='int8')
            tree = quantize_params(tree)
        if tensor_parallel and tensor_parallel > 1:
            # Keep the tree HOST-side: the engine device_puts each leaf
            # straight onto its mesh sharding — a 70B must never
            # materialize on chip 0.
            params = {'params': tree}
        else:
            params = {'params': jax.tree.map(jnp.asarray, tree)}
            del tree  # free the host copy for the server's lifetime
        model = model_config.name
        if tokenizer_name is None:
            tokenizer_name = hf_model
            tokenizer_implied = True
    else:
        from skypilot_tpu.models import get_model_config
        model_config = get_model_config(model)
        if weight_dtype == 'int8':
            from skypilot_tpu.models.llama import LlamaConfig
            if not isinstance(model_config, LlamaConfig):
                raise ValueError(
                    '--weight-dtype int8 currently supports the llama '
                    f'family; got {type(model_config).__name__}')
            model_config = dataclasses.replace(model_config,
                                               weight_dtype='int8')
    tokenizer = None
    if tokenizer_name:
        from transformers import AutoTokenizer
        try:
            tokenizer = AutoTokenizer.from_pretrained(tokenizer_name)
        except Exception as e:  # noqa: BLE001 — tokenizer is optional
            if not tokenizer_implied:
                raise  # explicitly requested: fail loudly
            # Checkpoint dir without tokenizer files: serve token-only.
            print(f'warning: no tokenizer in {tokenizer_name} ({e}); '
                  '/generate_text disabled, /generate (token API) works')
            tokenizer = None
        if eos_id is None and tokenizer is not None:
            eos_id = getattr(tokenizer, 'eos_token_id', None)
    cfg = InferConfig(model=model, num_slots=num_slots,
                      max_cache_len=max_cache_len, eos_id=eos_id,
                      decode_steps=decode_steps,
                      prefills_per_gap=prefills_per_gap,
                      cache_dtype=resolve_cache_dtype(cache_dtype),
                      draft_len=draft_len, ngram_max=ngram_max,
                      max_prefixes=max_prefixes, lora_rank=lora_rank,
                      lora_max_adapters=lora_max_adapters,
                      adaptive_decode_window=adaptive_window,
                      decode_lookahead=decode_lookahead,
                      prefill_chunk=prefill_chunk,
                      kv_block_size=kv_block_size, kv_blocks=kv_blocks,
                      auto_prefix_cache=auto_prefix_cache,
                      host_kv_bytes=host_kv_bytes,
                      qos=qos,
                      qos_tenant_weights=parse_tenant_weights(
                          qos_tenant_weights))
    # The ONE mesh-construction path every TP replica shares (server
    # entrypoint, chaos harness, tests): parallel.tp_mesh returns None
    # for degree <= 1, so DP and TP replicas flow through one line.
    from skypilot_tpu.parallel import tp_mesh
    mesh = tp_mesh(tensor_parallel or 0)
    engine = InferenceEngine(model_config, cfg, params=params, mesh=mesh)
    serve(engine, host=host, port=port, tokenizer=tokenizer,
          max_projected_ttft_s=max_ttft, max_queue=max_queue,
          adapter_dir=adapter_dir, auto_prefix=auto_prefix,
          warmup=warmup)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--model', default='llama-1b')
    parser.add_argument('--port', type=int, default=8100)
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--num-slots', type=int, default=8)
    parser.add_argument('--max-cache-len', type=int, default=2048)
    parser.add_argument('--tokenizer', default=None,
                        help='HF tokenizer name (optional)')
    parser.add_argument('--eos-id', type=int, default=None)
    parser.add_argument('--decode-steps', type=int, default=8)
    parser.add_argument('--hf-model', default=None,
                        help='HF Llama checkpoint (local path/cache): '
                             'serve real pretrained weights')
    parser.add_argument('--cache-dtype', default='bfloat16',
                        choices=['bfloat16', 'fp8'])
    parser.add_argument('--tensor-parallel', type=int,
                        # The serve-plane replica manager exports the
                        # task's resources.tp_size here, so a replica
                        # launched by `skytpu serve up --tp-size N`
                        # shards itself without the YAML having to
                        # thread the flag through its run command.
                        default=int(os.environ.get(
                            'SKYTPU_SERVE_TP_SIZE', '0') or 0),
                        help='shard the model over N local chips '
                             '(default: $SKYTPU_SERVE_TP_SIZE or 0)')
    parser.add_argument('--draft-len', type=int, default=0,
                        help='speculative decoding: prompt-lookup draft '
                             'tokens per dispatch (0 disables)')
    parser.add_argument('--ngram-max', type=int, default=4,
                        help='longest n-gram tried when drafting')
    parser.add_argument('--max-prefixes', type=int, default=16,
                        help='resident prefix-KV entries for '
                             '/cache_prefix (LRU; 0 disables)')
    parser.add_argument('--lora-rank', type=int, default=0,
                        help='multi-LoRA serving: adapter rank '
                             '(0 disables; POST /load_adapter to load)')
    parser.add_argument('--lora-max-adapters', type=int, default=8,
                        help='resident adapter slots (--lora-rank)')
    parser.add_argument('--adapter-dir', default=None,
                        help='directory POST /load_adapter may read '
                             'from (unset: runtime loading disabled)')
    parser.add_argument('--adaptive-window', action='store_true',
                        help='queue-aware decode windows: short '
                             'dispatches only while arrivals wait')
    parser.add_argument('--decode-lookahead', action='store_true',
                        help='dispatch the next decode window before '
                             'reading the current one (hides the '
                             'host round trip from TPOT)')
    parser.add_argument('--auto-prefix', action='store_true',
                        help='automatic prefix caching: a prompt head '
                             'seen twice registers itself (bucket-'
                             'quantized); vLLM-APC analog')
    parser.add_argument('--prefill-chunk', type=int, default=0,
                        help='chunked prefill: split prompts into '
                             'N-token pieces interleaved between decode '
                             'windows, bounding the decode stall to one '
                             'chunk and lifting the largest-bucket '
                             'prompt cap (0 = monolithic prefill; must '
                             'divide --max-cache-len)')
    parser.add_argument('--kv-block-size', type=int, default=0,
                        help='block-paged KV cache: pool block size in '
                             'tokens (0 = dense slotted layout; must '
                             'divide --max-cache-len, every prefill '
                             'bucket, and --prefill-chunk). Decode '
                             'streams ceil(len/block)*block cached rows '
                             'per step instead of max_cache_len, and '
                             'prefix hits share blocks copy-free')
    parser.add_argument('--kv-blocks', type=int, default=None,
                        help='pool size in blocks (incl. the reserved '
                             'dump block). Default fully provisions '
                             'num_slots*max_cache_len/block + 1; smaller '
                             'pools oversubscribe HBM and admission-'
                             'defer requests whose worst-case demand '
                             'does not fit')
    parser.add_argument('--auto-prefix-cache', action='store_true',
                        help='engine-level automatic radix-tree prefix '
                             'caching over the paged KV pool (requires '
                             '--kv-block-size): completed prompts\' '
                             'full blocks become matchable, admitted '
                             'prompts reuse their longest block-aligned '
                             'cached prefix copy-free, unreferenced '
                             'leaves are LRU-evicted under pool '
                             'pressure. Supersedes the --auto-prefix '
                             'heuristic; /cache_prefix becomes optional '
                             'pinning')
    parser.add_argument('--host-kv-bytes', type=int,
                        default=int(os.environ.get(
                            'SKYTPU_SERVE_HOST_KV_BYTES', '0') or 0),
                        help='host-RAM KV tier budget in bytes '
                             '(requires --auto-prefix-cache): radix '
                             'blocks evicted under HBM pressure spill '
                             'to host RAM and restore on the next '
                             'prefix match, overlapped with the suffix '
                             'prefill (0 disables; default: '
                             '$SKYTPU_SERVE_HOST_KV_BYTES or 0)')
    parser.add_argument('--warmup', action='store_true',
                        default=os.environ.get(
                            'SKYTPU_SERVE_WARMUP', '') in
                        ('1', 'true', 'yes', 'on'),
                        help='compile EVERY enumerated jit root×bucket '
                             'shape before declaring ready (steady-'
                             'state TTFT from the first request; '
                             'default: $SKYTPU_SERVE_WARMUP or off)')
    parser.add_argument('--qos', action='store_true',
                        help='QoS scheduling: priority classes '
                             '(interactive > batch) + per-tenant '
                             'weighted-fair queueing, batch preemption '
                             'at chunk boundaries (with --prefill-chunk '
                             '+ --auto-prefix-cache), and typed '
                             'deadline shedding at dequeue')
    parser.add_argument('--qos-tenant-weights', default=None,
                        help='per-tenant WFQ weights, e.g. '
                             '"teamA=3,teamB=1" (unlisted tenants '
                             'weigh 1.0); requires --qos')
    args = parser.parse_args()
    run(model=args.model, host=args.host, port=args.port,
        num_slots=args.num_slots, max_cache_len=args.max_cache_len,
        tokenizer_name=args.tokenizer, eos_id=args.eos_id,
        decode_steps=args.decode_steps, hf_model=args.hf_model,
        cache_dtype=args.cache_dtype,
        tensor_parallel=args.tensor_parallel,
        draft_len=args.draft_len, ngram_max=args.ngram_max,
        max_prefixes=args.max_prefixes, lora_rank=args.lora_rank,
        lora_max_adapters=args.lora_max_adapters,
        adapter_dir=args.adapter_dir,
        adaptive_window=args.adaptive_window,
        decode_lookahead=args.decode_lookahead,
        auto_prefix=args.auto_prefix,
        prefill_chunk=args.prefill_chunk,
        kv_block_size=args.kv_block_size, kv_blocks=args.kv_blocks,
        auto_prefix_cache=args.auto_prefix_cache,
        host_kv_bytes=args.host_kv_bytes,
        qos=args.qos, qos_tenant_weights=args.qos_tenant_weights,
        warmup=args.warmup)


if __name__ == '__main__':
    main()
