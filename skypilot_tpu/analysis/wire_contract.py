"""WIRE pass: cross-plane JSON wire-schema drift.

The three planes (engine server, load balancer, controller) talk
through string-keyed JSON documents whose producers and consumers live
in different files — nothing but convention keeps them aligned.  This
pass extracts, per *surface* (one named JSON document), the keys every
producer emits (via the dict-key lattice in ``analysis.dataflow``) and
the keys every registered consumer reads, then reports:

- **WIRE001** (error tier): a consumed key no producer emits — or one
  emitted only on *some* producer branch (e.g. paged-only engine stats
  keys read by a consumer that may face a dense replica).  These are
  the live drift bugs.
- **WIRE002** (baseline tier): a produced key nothing consumes.  Most
  are legitimate operator/dashboard surface — annotate the producing
  line ``# wire-ok: <reason>`` to declare that on the record (the
  reason is mandatory prose, reviewed like code) instead of carrying
  the finding in ``skycheck_baseline.txt`` forever; unannotated new
  orphans still surface.
- **WIRE003** (error tier): one key produced with conflicting concrete
  value types across branches/producers of the same surface.

The surface registry below is explicit, like jit_boundary.HOT_ROOTS:
adding an HTTP endpoint or a cross-plane reader means adding a spec
line here — skycheck then owns the contract forever after.

The pass is tree-scoped (``check_tree``): it needs producer and
consumer files together.  ``contract()`` returns the full
produced/consumed table; ``render_markdown()`` formats it for the
generated table in docs/architecture.md.
"""
import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.analysis import dataflow
from skypilot_tpu.analysis.findings import Finding

PASS_CONSUMED_NOT_PRODUCED = 'WIRE001'
PASS_PRODUCED_NOT_CONSUMED = 'WIRE002'
PASS_TYPE_CONFLICT = 'WIRE003'

# `# wire-ok: <reason>` on a producing line: the key is deliberately
# operator/dashboard-only surface — suppress its WIRE002 orphan
# finding at the declaration site.
_WIRE_OK_RE = re.compile(r'#\s*wire-ok\b')


def _wire_ok(files: Dict[str, str], path: str, line: int) -> bool:
    text = files.get(path)
    if text is None:
        return False
    lines = text.splitlines()
    return 0 < line <= len(lines) and \
        bool(_WIRE_OK_RE.search(lines[line - 1]))


@dataclasses.dataclass(frozen=True)
class Producer:
    path: str                  # repo-relative producer file
    func: str                  # qualname (suffix ok) of the producer
    mode: Tuple[str, ...]      # dataflow.dict_key_model mode


@dataclasses.dataclass(frozen=True)
class Consumer:
    path: str
    func: str
    vars: Optional[Tuple[str, ...]] = None   # doc receivers; None=any
    exclude_vars: Tuple[str, ...] = ()       # receivers to skip
    route: Optional[str] = None              # scope to one If branch
    #   whose test compares against this constant (multi-route handler)


@dataclasses.dataclass(frozen=True)
class SurfaceSpec:
    name: str
    producers: Tuple[Producer, ...]
    consumers: Tuple[Consumer, ...]
    # Event streams (SSE) carry a UNION of event types: consumers
    # dispatch on discriminator keys, so branch-dependent production is
    # the design, not drift — only consumed-never-produced is an error.
    union_producers: bool = False


_SERVER = 'skypilot_tpu/infer/server.py'
_ENGINE = 'skypilot_tpu/infer/engine.py'
_LB = 'skypilot_tpu/serve/load_balancer.py'
_CTRL = 'skypilot_tpu/serve/controller.py'
_POLICIES = 'skypilot_tpu/serve/load_balancing_policies.py'
_BATCH = 'skypilot_tpu/serve/batch.py'
_AUTOSCALERS = 'skypilot_tpu/serve/autoscalers.py'

# The wire contract: every cross-plane JSON document the system
# exchanges.  Producer modes: ('return',) = returned dict,
# ('var', N) = dict bound to local N (+ its N[k]= mutations),
# ('call', F) = first arg of every F(...) call in the function.
SURFACES: Tuple[SurfaceSpec, ...] = (
    # Engine-plane /stats HTTP document (server.py builds it inline in
    # the route handler around engine.stats()).
    SurfaceSpec(
        '/stats',
        producers=(Producer(_SERVER, 'do_GET', ('route-stats',)),),
        consumers=(
            Consumer('tests/test_infer.py',
                     'test_openai_completions_token_array',
                     vars=('stats',)),
        ),
    ),
    # Engine-plane /healthz readiness document: the LB probe thread and
    # the routing policies read it on the routing-critical path.
    SurfaceSpec(
        '/healthz',
        producers=(Producer(_SERVER, 'health', ('var', 'doc')),),
        consumers=(
            Consumer(_LB, '_probe_replica_once', vars=('doc',)),
            Consumer(_POLICIES, 'PrefixAffinityPolicy.observe_replica',
                     vars=('health_doc',)),
            Consumer(_SERVER, 'do_GET', vars=('doc',)),
        ),
    ),
    # The kv sub-document of /healthz (engine.kv_health()): consumed by
    # prefix-affinity routing (block_size keys the ring, occupancy
    # feeds the load penalty).
    SurfaceSpec(
        '/healthz.kv',
        producers=(Producer(_ENGINE, 'kv_health', ('return',)),),
        consumers=(
            Consumer(_POLICIES, 'PrefixAffinityPolicy.observe_replica',
                     vars=('kv',)),
            Consumer(_POLICIES, 'PrefixAffinityPolicy._eff_load',
                     vars=None),
            Consumer(_POLICIES, 'PrefixAffinityPolicy._load_bound',
                     vars=None, exclude_vars=('radix',)),
            # The LB probe records kv.tp per replica (TP vs DP fleet
            # composition), relayed to the controller via lb_sync.
            Consumer(_LB, '_probe_replica_once', vars=('kv',)),
        ),
    ),
    # The host_tier sub-document of /healthz.kv (and /stats['kv']):
    # host-RAM KV tier occupancy + spill/restore counters.  One
    # producer serves BOTH the enabled and disabled branches with the
    # same key set — branch stability is the contract.
    SurfaceSpec(
        '/healthz.kv.host_tier',
        producers=(Producer(_ENGINE, '_host_tier_section',
                            ('return',)),),
        consumers=(
            Consumer(_LB, '_probe_replica_once', vars=('ht',)),
            Consumer(_LB, 'lb_stats', vars=('ht',)),
            Consumer('tests/test_kv_tier.py', None, vars=('ht',)),
        ),
    ),
    # The radix sub-document of /healthz.kv: the affinity load bound
    # boosts its spill threshold by the fleet-average hit rate.
    SurfaceSpec(
        '/healthz.kv.radix',
        producers=(Producer(_ENGINE, 'kv_health', ('var', 'radix')),),
        consumers=(
            Consumer(_POLICIES, 'PrefixAffinityPolicy._load_bound',
                     vars=('radix',)),
        ),
    ),
    # LB-plane /lb/stats observability document (batch row-lease
    # counters included: the chaos harness asserts lease adoption
    # across an LB restart off this surface).
    SurfaceSpec(
        '/lb/stats',
        producers=(Producer(_LB, 'lb_stats', ('return',)),),
        consumers=(
            Consumer('tests/test_serve_failover.py', None,
                     vars=('stats', 'st')),
            Consumer('tests/test_lb_affinity.py', None,
                     vars=('stats', 'st')),
            Consumer('tests/test_kv_tier.py', None,
                     vars=('stats', 'st')),
            Consumer('tests/test_control_plane.py', None,
                     vars=('stats', 'st')),
            Consumer('tests/test_batch_plane.py', None,
                     vars=('stats',)),
            Consumer('scripts/bench_serve_lb.py', None,
                     vars=('stats',)),
            Consumer('scripts/chaos_smoke.py', None,
                     vars=('stats', 'lb_stats')),
        ),
    ),
    # Batch-plane job-status document: the POST /v1/batches response
    # carries it under 'status', GET /v1/batches/<id> returns it bare
    # (controller.batch_status is a pass-through).
    SurfaceSpec(
        '/v1/batches.status',
        producers=(Producer(_BATCH, 'BatchCoordinator.status',
                            ('return',)),),
        consumers=(
            Consumer('tests/test_batch_plane.py', None,
                     vars=('st', 'resumed')),
            Consumer('scripts/chaos_smoke.py', 'batch_sweep',
                     vars=('st', 'before', 'final')),
        ),
    ),
    # Batch backlog -> autoscaler signal: rows remaining + measured
    # completion rate drive the backlog scale-up term.
    SurfaceSpec(
        'batch.backlog',
        producers=(Producer(_BATCH, 'BatchCoordinator.backlog',
                            ('return',)),),
        consumers=(
            Consumer(_AUTOSCALERS,
                     'SloLatencyAutoscaler._batch_meets_window',
                     vars=('b',)),
            Consumer('tests/test_batch_plane.py', None,
                     vars=('b',)),
        ),
    ),
    # Controller /controller/state snapshot.
    SurfaceSpec(
        '/controller/state',
        producers=(Producer(_CTRL, 'state_snapshot', ('return',)),),
        consumers=(
            Consumer('tests/test_qos.py', None, vars=('snap',)),
            Consumer('tests/test_serve.py', None, vars=('snap',)),
            Consumer('tests/test_control_plane.py', None,
                     vars=('snap',)),
        ),
    ),
    # LB -> controller sync body (one producer, one consumer, different
    # processes: the canonical drift surface).
    SurfaceSpec(
        'lb_sync',
        producers=(Producer(_LB, '_sync_with_controller_once',
                            ('call', 'dumps')),),
        consumers=(
            Consumer(_CTRL, 'ServeController._handle',
                     vars=('payload',),
                     route='/controller/load_balancer_sync'),
        ),
    ),
    # Engine-plane /generate SSE terminal events (done/error): consumed
    # by the LB's stream relay for failover stitching.
    SurfaceSpec(
        'sse.events',
        producers=(
            Producer(_SERVER, '_stream', ('call', 'emit')),
            Producer(_LB, 'emit_error_event', ('call', 'emit_event')),
            Producer(_LB, '_handle_stream_generate',
                     ('call', 'emit_event')),
        ),
        consumers=(
            Consumer(_LB, '_proxy_stream_once', vars=('obj',)),
        ),
        union_producers=True,
    ),
    # engine.stats() itself (the dict under /stats['kv_cache'] and the
    # flat alias tier): branch-stability matters because dashboards
    # read it for BOTH layouts.
    SurfaceSpec(
        'engine.stats',
        producers=(Producer(_ENGINE, 'stats', ('return',)),),
        consumers=(
            Consumer(_SERVER, 'do_GET', vars=('st',)),
            Consumer('tests/test_paged_kv.py', None, vars=('st',)),
            Consumer('tests/test_radix.py', None, vars=('st',)),
        ),
    ),
)


def _producer_model(files: Dict[str, str], spec: Producer
                    ) -> Optional[dataflow.KeyModel]:
    text = files.get(spec.path)
    if text is None:
        return None
    try:
        index = _index_for(spec.path, text)
    except SyntaxError:
        return None
    if spec.mode == ('route-stats',):
        return _route_stats_model(index)
    fn = index.find(spec.func)
    if fn is None:
        return None
    return dataflow.dict_key_model(index, fn, spec.mode)


def _route_stats_model(index: dataflow.ModuleIndex
                       ) -> Optional[dataflow.KeyModel]:
    """The dict literal server.do_GET answers on the '/stats' route —
    anchored on the route string, so handler refactors don't lose it."""
    fn = index.find('do_GET')
    if fn is None:
        return None
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        if isinstance(test, ast.Compare) and test.comparators and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value == '/stats':
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == '_json' and \
                        len(sub.args) >= 2 and \
                        isinstance(sub.args[1], ast.Dict):
                    model = dataflow.KeyModel()
                    dataflow._literal_keys(index, fn, sub.args[1],
                                           model, conditional=False)
                    return model
    return None


_INDEX_CACHE: Dict[Tuple[str, int], dataflow.ModuleIndex] = {}


def _index_for(path: str, text: str) -> dataflow.ModuleIndex:
    key = (path, hash(text))
    idx = _INDEX_CACHE.get(key)
    if idx is None:
        if len(_INDEX_CACHE) > 64:   # bound memory across test runs
            _INDEX_CACHE.clear()
        idx = dataflow.ModuleIndex(path, text)
        _INDEX_CACHE[key] = idx
    return idx


def _consumer_keys(files: Dict[str, str], spec: Consumer
                   ) -> Dict[str, Tuple[int, str]]:
    """key -> (line, 'path:func') over one consumer spec."""
    text = files.get(spec.path)
    if text is None:
        return {}
    try:
        index = dataflow.ModuleIndex(spec.path, text)
    except SyntaxError:
        return {}
    fns: List[dataflow.FunctionInfo] = []
    if spec.func is None:
        fns = list(index.functions.values())
    else:
        fn = index.find(spec.func)
        if fn is not None:
            fns = [fn]
    out: Dict[str, Tuple[int, str]] = {}
    for fn in fns:
        scope = None
        if spec.route is not None:
            scope = _route_branch(fn.node, spec.route)
            if scope is None:
                continue
        for key, line in dataflow.read_keys(
                index, fn, spec.vars,
                exclude_vars=spec.exclude_vars, scope=scope).items():
            out.setdefault(key, (line, f'{spec.path}:{fn.qualname}'))
    return out


def _route_branch(fn_node: ast.AST, route: str) -> Optional[ast.AST]:
    """The body of the If branch inside ``fn_node`` whose test compares
    against the constant ``route`` — scopes a multi-route handler's
    reads to one wire surface.  Only the branch *body*: an elif chain
    keeps its other routes in ``orelse``."""
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.If):
            continue
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Constant) and sub.value == route:
                return ast.Module(body=node.body, type_ignores=[])
    return None


@dataclasses.dataclass
class SurfaceContract:
    name: str
    produced: dataflow.KeyModel
    consumed: Dict[str, Tuple[int, str]]
    producer_of: Dict[str, Tuple[str, int]]   # key -> (path, line)
    producer_path: str
    union_producers: bool = False


def contract(files: Dict[str, str],
             surfaces: Sequence[SurfaceSpec] = SURFACES
             ) -> List[SurfaceContract]:
    out: List[SurfaceContract] = []
    for spec in surfaces:
        produced: Optional[dataflow.KeyModel] = None
        producer_path = spec.producers[0].path
        producer_of: Dict[str, Tuple[str, int]] = {}
        for p in spec.producers:
            model = _producer_model(files, p)
            if model is None:
                continue
            for key, line in model.lines.items():
                producer_of.setdefault(key, (p.path, line))
            if produced is None:
                produced = model
            else:
                # Multiple producers of one surface are alternatives
                # (e.g. engine done event vs LB synthesized terminal):
                # 'always' means every producer emits it.
                produced.merge_branch(model)
        if produced is None:
            produced = dataflow.KeyModel(complete=False)
        consumed: Dict[str, Tuple[int, str]] = {}
        for c in spec.consumers:
            for key, loc in _consumer_keys(files, c).items():
                consumed.setdefault(key, loc)
        out.append(SurfaceContract(spec.name, produced, consumed,
                                   producer_of, producer_path,
                                   spec.union_producers))
    return out


def check_tree(files: Dict[str, str],
               surfaces: Sequence[SurfaceSpec] = SURFACES
               ) -> List[Finding]:
    findings: List[Finding] = []
    for sc in contract(files, surfaces):
        prod = sc.produced
        for key, (line, where) in sorted(sc.consumed.items()):
            path, _, func = where.partition(':')
            if key not in prod.keys:
                if not prod.complete:
                    # The producer model has unresolved spreads: a
                    # missing key is unprovable — stay quiet rather
                    # than cry wolf on every consumer.
                    continue
                findings.append(Finding(
                    path, line, PASS_CONSUMED_NOT_PRODUCED,
                    f"surface '{sc.name}': key '{key}' consumed by "
                    f'{func} but never produced'))
            elif key in prod.sometimes and not sc.union_producers:
                findings.append(Finding(
                    path, line, PASS_CONSUMED_NOT_PRODUCED,
                    f"surface '{sc.name}': key '{key}' consumed by "
                    f'{func} but produced only on some branches '
                    '(layout/feature-dependent producers must emit a '
                    'stable key set)'))
        for key in sorted(prod.keys):
            if key not in sc.consumed:
                ppath, pline = sc.producer_of.get(
                    key, (sc.producer_path, 1))
                if _wire_ok(files, ppath, pline):
                    continue
                findings.append(Finding(
                    ppath, pline, PASS_PRODUCED_NOT_CONSUMED,
                    f"surface '{sc.name}': key '{key}' produced but "
                    'no registered consumer reads it'))
        for key, types in sorted(prod.types.items()):
            concrete = types - {'unknown', 'none'}
            if len(concrete) > 1:
                ppath, pline = sc.producer_of.get(
                    key, (sc.producer_path, 1))
                findings.append(Finding(
                    ppath, pline, PASS_TYPE_CONFLICT,
                    f"surface '{sc.name}': key '{key}' produced with "
                    f'conflicting value types '
                    f'{"/".join(sorted(concrete))}'))
    return findings


def render_markdown(files: Dict[str, str],
                    surfaces: Sequence[SurfaceSpec] = SURFACES) -> str:
    """The generated wire-contract table for docs/architecture.md."""
    rows = ['| surface | producer | stable keys | branch-dependent | '
            'consumed |',
            '|---|---|---|---|---|']
    for sc in contract(files, surfaces):
        stable = ', '.join(f'`{k}`' for k in sorted(sc.produced.always))
        branchy = ', '.join(f'`{k}`'
                            for k in sorted(sc.produced.sometimes))
        consumed = ', '.join(f'`{k}`' for k in sorted(sc.consumed))
        rows.append(f'| `{sc.name}` | `{sc.producer_path}` | '
                    f'{stable or "—"} | {branchy or "—"} | '
                    f'{consumed or "—"} |')
    return '\n'.join(rows) + '\n'
