"""Shared source-file discovery for skycheck and the tier-1 tooling.

One walker, one exclusion set: ``scripts/skycheck.py`` uses it to find
the Python sources to analyze, and ``scripts/check_tier1_budget.py``
uses it to validate ``--require`` paths against the test files that
actually exist on disk.  Keeping the logic here (instead of two ad-hoc
``os.walk`` loops) is what keeps ``tests/__pycache__``,
``scripts/__pycache__`` and other generated artifacts out of BOTH
tools at once.
"""
import os
from typing import Iterable, Iterator, Optional

# Directory basenames that never contain hand-written sources.
EXCLUDED_DIR_NAMES = frozenset({
    '__pycache__', '.git', '.hg', '.pytest_cache', '.mypy_cache',
    '.ruff_cache', '.ipynb_checkpoints', 'build', 'dist', 'node_modules',
    '.eggs', '.venv', 'venv', '.tox',
})


def _excluded_dir(name: str) -> bool:
    return (name in EXCLUDED_DIR_NAMES or name.endswith('.egg-info')
            or name.startswith('.'))


def iter_py_files(root: str,
                  subdirs: Optional[Iterable[str]] = None
                  ) -> Iterator[str]:
    """Yield repo-relative paths ('/'-separated) of every ``.py`` file
    under ``root`` (or under ``root/<subdir>`` for each of ``subdirs``),
    skipping generated/vendored directories.  Deterministic order.
    """
    tops = ([os.path.join(root, s) for s in subdirs]
            if subdirs is not None else [root])
    for top in tops:
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if not _excluded_dir(d))
            for fn in sorted(filenames):
                if not fn.endswith('.py'):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                yield rel.replace(os.sep, '/')
