"""BLOCK pass: path-sensitive paged-block ownership proofs.

``_alloc_blocks`` hands out refcount-1 block ids as a plain Python
list; nothing but discipline makes that list reach EXACTLY ONE of the
legal ownership sinks on every path — including the exception edges a
mid-function jit dispatch can take:

- **free**: ``for b in VAR: self._deref_block(b)`` (or a scalar
  ``self._deref_block(VAR)``) — refs returned to the pool.
- **table**: ``self._tables_np[...] = VAR`` — the slot table adopts
  the refs (``_free_slot_blocks`` releases them at slot teardown).
- **entry**: ``self._prefixes[...] = {... VAR ...}`` — the resident
  prefix registry adopts (eviction derefs).
- **radix**: ``self._radix.insert(..., VAR, ..., own=True)`` — the
  radix tree takes over the allocation refs (duplicates are dereffed
  inside insert, eviction derefs the rest).
- **spill**: ``self._host_tier.spill(..., VAR, ...)`` — the host-RAM
  tier adopts the blocks across the tier boundary (its LRU/budget
  trim is the eventual release).  A RESTORED block needs no special
  kind: the restore path allocates fresh blocks through
  ``_alloc_blocks`` and hands them to the radix sink, so it re-enters
  the ordinary conservation proof.

The static complement of the runtime refcount sanitizer
(``sanitizers.check_block_conservation``): the sanitizer proves the
pool balanced at a quiesce point it actually reached; this pass proves
no path — taken or not — can leak or double-release.

Allocation sites are annotated in source::

    blocks = self._alloc_blocks(need)   # owns-blocks: entry

naming which sink kinds the site may use ('free' is always legal —
every owner must be able to unwind).  An UNannotated alloc site is
still analyzed, with every sink kind allowed: new call sites never
silently escape the proof, the annotation only narrows intent.

- **BLOCK001** (leak-on-path): some path from the allocation reaches a
  return / raise / escaping-exception edge / loop-iteration end while
  still owning the list.
- **BLOCK002** (double-release-on-path): a path releases the same list
  twice, or through a sink kind the annotation forbids.

Exception edges are modeled for the calls that really do raise on the
hot path: the jitted dispatch roots (shape-bucket mismatches, runtime
XLA failures, fault injection) and explicit ``raise`` statements.
"""
import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from skypilot_tpu.analysis.findings import Finding

PASS_LEAK = 'BLOCK001'
PASS_DOUBLE_FREE = 'BLOCK002'

# Files whose alloc sites this pass owns (mirrors jit_boundary's
# explicit HOT_ROOTS config).
OWNED_FILES = (
    'skypilot_tpu/infer/engine.py',
    'skypilot_tpu/infer/radix.py',
    'skypilot_tpu/infer/block_pool.py',
)

ALLOC_FUNCS = frozenset({'_alloc_blocks'})

# self-method calls treated as may-raise while owning (the jitted
# dispatch roots: first-call tracing, shape mismatches, and injected
# faults all surface here).
RAISING_CALLS = frozenset({
    '_paged_prefill', '_paged_decode', '_paged_spec_verify',
    '_paged_copy_blocks', '_prefill_insert', '_chunk_prefill',
    '_decode', '_spec_verify', '_prefill_capture', '_prefix_prefill',
    '_alloc_blocks', '_paged_restore_blocks',
})

ALL_KINDS = frozenset({'free', 'table', 'entry', 'radix', 'spill'})

_ANNOT_RE = re.compile(r'#\s*owns-blocks:\s*([a-z,\s]+)')

# Ownership states for one symbolic allocation instance.
_INERT = 'INERT'     # before the allocation executes
_OWNED = 'OWNED'     # refs held by the local var
_DONE = 'DONE'       # refs handed to exactly one sink


def _annotation_kinds(lines: Sequence[str], lineno: int
                      ) -> Optional[frozenset]:
    """Sink kinds allowed by the ``# owns-blocks:`` comment on the
    alloc line (or the line above).  None = unannotated (all kinds)."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _ANNOT_RE.search(lines[ln - 1])
            if m:
                kinds = frozenset(
                    k.strip() for k in m.group(1).split(',')
                    if k.strip()) & ALL_KINDS
                # 'free' is always legal: every owner must be able to
                # unwind on the exception edge.
                return (kinds | {'free'}) if kinds else ALL_KINDS
    return None


def _self_call_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            isinstance(node.func.value, ast.Name) and \
            node.func.value.id == 'self':
        return node.func.attr
    return None


def _alloc_target(stmt: ast.stmt) -> Optional[Tuple[str, ast.Call]]:
    """(var, call) when stmt is ``VAR = self._alloc_blocks(...)`` or
    ``[VAR] = self._alloc_blocks(...)``."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    if _self_call_name(stmt.value) not in ALLOC_FUNCS:
        return None
    tgt = stmt.targets[0]
    if isinstance(tgt, ast.Name):
        return tgt.id, stmt.value
    if isinstance(tgt, (ast.List, ast.Tuple)) and \
            len(tgt.elts) == 1 and isinstance(tgt.elts[0], ast.Name):
        return tgt.elts[0].id, stmt.value
    return None


def _mentions_name(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _release_kind(stmt: ast.stmt, var: str
                  ) -> Optional[Tuple[str, int]]:
    """(kind, line) when stmt hands ``var``'s refs to a sink."""
    # free: for b in VAR: self._deref_block(b)
    if isinstance(stmt, ast.For) and \
            isinstance(stmt.iter, ast.Name) and stmt.iter.id == var:
        for sub in ast.walk(stmt):
            if _self_call_name(sub) == '_deref_block':
                return 'free', stmt.lineno
        return None
    if isinstance(stmt, ast.Expr):
        # free (scalar): self._deref_block(VAR)
        call = stmt.value
        if _self_call_name(call) == '_deref_block' and call.args and \
                isinstance(call.args[0], ast.Name) and \
                call.args[0].id == var:
            return 'free', stmt.lineno
    # The remaining sinks live in SIMPLE statements only — a compound
    # statement containing one deep in its body is not itself the
    # release (the walker descends and finds it with accurate states).
    if not isinstance(stmt, (ast.Expr, ast.Assign, ast.AugAssign,
                             ast.AnnAssign)):
        return None
    # radix: self._radix.insert(..., VAR, ..., own=True) — the call
    # may feed an Assign/AugAssign.
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == 'insert' and \
                isinstance(sub.func.value, ast.Attribute) and \
                sub.func.value.attr == '_radix':
            owns = any(kw.arg == 'own' and
                       isinstance(kw.value, ast.Constant) and
                       kw.value.value is True
                       for kw in sub.keywords)
            if owns and any(_mentions_name(a, var) for a in sub.args):
                return 'radix', sub.lineno
    # spill: self._host_tier.spill(..., VAR, ...) — the host-RAM tier
    # adopts the blocks across the tier boundary.
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == 'spill' and \
                isinstance(sub.func.value, ast.Attribute) and \
                sub.func.value.attr == '_host_tier':
            if any(_mentions_name(a, var) for a in sub.args):
                return 'spill', sub.lineno
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        tgt = stmt.targets[0]
        if isinstance(tgt, ast.Subscript) and \
                isinstance(tgt.value, ast.Attribute):
            # table: self._tables_np[...] = VAR
            if tgt.value.attr == '_tables_np' and \
                    _mentions_name(stmt.value, var):
                return 'table', stmt.lineno
            # entry: self._prefixes[...] = {... VAR ...}
            if tgt.value.attr == '_prefixes' and \
                    _mentions_name(stmt.value, var):
                return 'entry', stmt.lineno
    return None


def _may_raise(stmt: ast.stmt) -> Optional[int]:
    """Line of the first may-raise call inside stmt (nested
    defs/lambdas excluded — they don't run here)."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        name = _self_call_name(node)
        if name in RAISING_CALLS:
            return node.lineno
        stack.extend(ast.iter_child_nodes(node))
    return None


class _SiteProof:
    """Abstract interpretation of ONE allocation site: every statement
    path from function entry, tracking {INERT, OWNED, DONE} for the
    allocated list.  Findings dedupe on (line, pass, message)."""

    def __init__(self, path: str, fn_name: str, alloc_stmt: ast.stmt,
                 var: str, kinds: frozenset) -> None:
        self.path = path
        self.fn_name = fn_name
        self.alloc_stmt = alloc_stmt
        self.var = var
        self.kinds = kinds
        self.findings: Dict[Tuple[int, str, str], Finding] = {}

    def _emit(self, line: int, pass_id: str, msg: str) -> None:
        key = (line, pass_id, msg)
        if key not in self.findings:
            self.findings[key] = Finding(self.path, line, pass_id, msg)

    def _leak(self, line: int, how: str) -> None:
        self._emit(line, PASS_LEAK,
                   f'{self.fn_name}: blocks allocated at line '
                   f'{self.alloc_stmt.lineno} leak {how}')

    # -- statement-list walker -------------------------------------
    # run() returns the fall-through states; terminal paths (return /
    # raise routed to a handler / escaping exception) contribute none.
    # try_stack holds per-enclosing-Try collectors of raise states.

    def run(self, stmts: Sequence[ast.stmt], states: Set[str],
            try_stack: List[Set[str]]) -> Set[str]:
        cur = set(states)
        for stmt in stmts:
            if not cur:
                return cur
            cur = self._step(stmt, cur, try_stack)
        return cur

    def _raise_edge(self, states: Set[str], line: int,
                    try_stack: List[Set[str]], how: str) -> None:
        """An exception launches from here: route to the innermost
        handler, or report the owning states that escape."""
        if try_stack:
            try_stack[-1] |= states
            return
        if _OWNED in states:
            self._leak(line, how)

    def _step(self, stmt: ast.stmt, cur: Set[str],
              try_stack: List[Set[str]]) -> Set[str]:
        # The allocation itself: may raise BEFORE owning (safe), then
        # transitions INERT -> OWNED.
        if stmt is self.alloc_stmt:
            return {_OWNED if s == _INERT else s for s in cur}

        rel = _release_kind(stmt, self.var)
        if rel is not None:
            kind, line = rel
            if _DONE in cur:
                self._emit(line, PASS_DOUBLE_FREE,
                           f'{self.fn_name}: blocks allocated at line '
                           f'{self.alloc_stmt.lineno} already released '
                           f'on some path reaching this {kind} sink')
            if _OWNED in cur and kind not in self.kinds:
                self._emit(line, PASS_DOUBLE_FREE,
                           f"{self.fn_name}: sink kind '{kind}' not "
                           f'permitted by the owns-blocks annotation '
                           f'at line {self.alloc_stmt.lineno} '
                           f'(allowed: {",".join(sorted(self.kinds))})')
            return {_DONE if s == _OWNED else s for s in cur}

        # Rebinding the var while owning loses the only handle.
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == self.var
                for t in stmt.targets) and _OWNED in cur:
            self._leak(stmt.lineno, 'when the variable is rebound')
            return {_DONE if s == _OWNED else s for s in cur}

        if isinstance(stmt, ast.Return):
            if _OWNED in cur:
                self._leak(stmt.lineno, 'on this return path')
            return set()
        if isinstance(stmt, ast.Raise):
            self._raise_edge(cur, stmt.lineno, try_stack,
                             'on this raise path')
            return set()

        if isinstance(stmt, ast.If):
            out = self.run(stmt.body, cur, try_stack)
            out |= self.run(stmt.orelse, cur, try_stack)
            return out
        if isinstance(stmt, ast.With):
            return self.run(stmt.body, cur, try_stack)
        if isinstance(stmt, (ast.For, ast.While)):
            contains_alloc = any(
                sub is self.alloc_stmt for sub in ast.walk(stmt))
            if contains_alloc:
                # Each iteration allocates a FRESH instance: the body
                # always enters with no live allocation, and an OWNED
                # state surviving to the iteration end is a leak (the
                # next iteration rebinds the variable).
                body_states = self.run(stmt.body, {_INERT}, try_stack)
                if _OWNED in body_states:
                    self._leak(stmt.lineno,
                               'at the end of the loop iteration that '
                               'allocated them (next iteration rebinds'
                               ' the variable)')
                    body_states = {_DONE if s == _OWNED else s
                                   for s in body_states}
                out = cur | body_states
            else:
                body_states = set(cur)
                out = set(cur)      # zero iterations
                for _ in range(3):  # fixpoint over tiny state space
                    body_states = self.run(stmt.body, body_states,
                                           try_stack)
                    if body_states <= out:
                        break
                    out |= body_states
            out |= self.run(stmt.orelse, out, try_stack)
            return out
        if isinstance(stmt, ast.Try):
            collector: Set[str] = set()
            try_stack.append(collector)
            body_out = self.run(stmt.body, cur, try_stack)
            try_stack.pop()
            out = self.run(stmt.orelse, body_out, try_stack) \
                if stmt.orelse else body_out
            for handler in stmt.handlers:
                out |= self.run(handler.body, set(collector),
                                try_stack)
            if stmt.finalbody:
                out = self.run(stmt.finalbody, out, try_stack)
            return out
        # Simple statement: a may-raise dispatch forks an exception
        # edge with the state AT this statement, then falls through.
        # (Compound statements descend instead — their inner simple
        # statements fire the edge with accurate post-release states.)
        raise_line = _may_raise(stmt)
        if raise_line is not None:
            self._raise_edge(
                cur, raise_line, try_stack,
                'if the jitted dispatch raises (fault injection, '
                'shape-bucket miss, runtime XLA failure)')
        return cur


def _own_stmts(fn_node: ast.AST) -> List[ast.stmt]:
    """Statements belonging to ``fn_node`` itself (nested defs get
    their own proofs when the module walk reaches them)."""
    out: List[ast.stmt] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.stmt):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_file(path: str, text: str) -> List[Finding]:
    if path not in OWNED_FILES:
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    lines = text.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        for stmt in _own_stmts(node):
            alloc = _alloc_target(stmt)
            if alloc is None:
                continue
            var, call = alloc
            kinds = _annotation_kinds(lines, call.lineno)
            proof = _SiteProof(path, node.name, stmt, var,
                               kinds if kinds is not None
                               else ALL_KINDS)
            final = proof.run(node.body, {_INERT}, [])
            if _OWNED in final:
                proof._leak(node.body[-1].lineno,
                            'when the function falls off its end')
            findings.extend(proof.findings.values())
    findings.sort(key=lambda f: (f.line, f.pass_id, f.message))
    return findings
