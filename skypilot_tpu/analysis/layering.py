"""LAYER pass: enforce the package dependency DAG.

The precondition for the engine decomposition (ROADMAP item 5) is that
the planes stay separable:

- ``skypilot_tpu/infer`` never imports ``skypilot_tpu.serve`` — the
  engine/replica plane must run without the control plane on the
  machine.  Declared exemption: ``infer/chaos.py``, the process-level
  chaos harness whose JOB is to wire killable replicas to the real LB
  (test-only tooling, not a data-plane dependency).
- ``skypilot_tpu/serve`` never imports ``skypilot_tpu.infer.engine``
  internals — the serve plane talks to replicas over HTTP, and any
  future in-process use goes through the ``skypilot_tpu.infer`` public
  surface, not engine internals.
- ``skypilot_tpu/ops`` imports neither — kernels are leaf modules.

Both absolute and relative imports are resolved; module- and
function-level imports are treated alike (a lazy import is still a
dependency).
"""
import ast
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.analysis.findings import Finding

PASS_ID = 'LAYER001'

# (source path prefix, forbidden module prefixes, {exempt path: reason})
Rule = Tuple[str, Sequence[str], Dict[str, str]]

RULES: List[Rule] = [
    ('skypilot_tpu/infer/', ('skypilot_tpu.serve',), {
        'skypilot_tpu/infer/chaos.py':
            'chaos harness drives the real serve plane by design',
    }),
    ('skypilot_tpu/serve/', ('skypilot_tpu.infer.engine',), {}),
    ('skypilot_tpu/ops/', ('skypilot_tpu.infer', 'skypilot_tpu.serve'),
     {}),
]


def _module_of(path: str) -> str:
    """'skypilot_tpu/infer/engine.py' -> 'skypilot_tpu.infer.engine'."""
    mod = path[:-3] if path.endswith('.py') else path
    if mod.endswith('/__init__'):
        mod = mod[:-len('/__init__')]
    return mod.replace('/', '.')


def _resolve_relative(path: str, level: int,
                      module: Optional[str]) -> str:
    """Absolute module named by ``from <dots><module> import ...``."""
    parts = _module_of(path).split('.')
    if not path.endswith('/__init__.py'):
        parts = parts[:-1]                 # containing package
    parts = parts[:len(parts) - (level - 1)] if level > 1 else parts
    if module:
        parts = parts + module.split('.')
    return '.'.join(parts)


def _imported_modules(tree: ast.AST, path: str):
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = _resolve_relative(path, node.level, node.module)
            else:
                base = node.module or ''
            yield node.lineno, base
            # `from pkg import engine` imports pkg.engine the module —
            # check one level deeper so renamed-module imports of a
            # forbidden submodule don't slip through.
            for alias in node.names:
                yield node.lineno, f'{base}.{alias.name}'


def _violates(mod: str, forbidden: Sequence[str]) -> Optional[str]:
    for prefix in forbidden:
        if mod == prefix or mod.startswith(prefix + '.'):
            return prefix
    return None


def check_file(path: str, text: str,
               rules: Optional[List[Rule]] = None) -> List[Finding]:
    rules = RULES if rules is None else rules
    active = [r for r in rules if path.startswith(r[0])
              and path not in r[2]]
    if not active:
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return []
    findings: List[Finding] = []
    seen = set()
    for lineno, mod in _imported_modules(tree, path):
        for src_prefix, forbidden, _ in active:
            hit = _violates(mod, forbidden)
            if hit and (lineno, hit) not in seen:
                seen.add((lineno, hit))
                findings.append(Finding(
                    path, lineno, PASS_ID,
                    f"layering violation: '{src_prefix}' must not "
                    f"import '{hit}' (import of '{mod}')"))
    return findings
