"""Shared dataflow scaffolding for the skycheck contract passes.

PR 6's passes were syntactic (one AST node at a time); the wire-schema,
block-lifecycle and compile-budget passes all need to answer the same
deeper question: *where does this value come from?*  This module is the
shared answer — a small, deliberately conservative def-use layer over
``ast`` with three capabilities:

- **ModuleIndex**: one parse of a file, functions indexed by dotted
  qualname (``Class.method``, ``outer.inner``) plus a call-site index
  so ``self.helper(...)`` argument expressions can be found from the
  callee side (the interprocedural step).
- **resolve_sources**: reduce an expression to the set of *source
  atoms* feeding it — constants, ``a.b.c`` attribute chains, calls (by
  dotted callee name), or function parameters.  Parameters resolve one
  level through the caller's argument expression (depth-bounded, cycle
  guarded); anything the walk cannot classify becomes an ``unknown``
  atom carrying the reason, so passes degrade to findings instead of
  silent blind spots.
- **KeyModel** (`dict_key_model`): the dict-key lattice of a JSON
  payload — which string keys a function's returned / emitted /
  assigned dict carries, whether each key is produced on *every* path
  or only some branch, and a best-effort value type per key (for the
  WIRE003 type-conflict check).

Everything here is pure ``ast`` — no imports of the analyzed modules,
so the passes run in milliseconds and never pay a jax import.
"""
import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    'Source', 'FunctionInfo', 'ModuleIndex', 'KeyModel',
    'dotted_name', 'local_defs', 'resolve_sources', 'dict_key_model',
    'infer_value_type', 'read_keys',
]


@dataclasses.dataclass(frozen=True, order=True)
class Source:
    """One atom feeding an expression.

    kind: 'const' | 'attr' | 'call' | 'param' | 'unknown'
    detail: const repr / dotted chain / callee name / param name /
    reason the walk gave up.
    """
    kind: str
    detail: str


def dotted_name(node: ast.AST) -> Optional[str]:
    """``self.cfg.kv_block_size`` -> that string; None when the chain
    bottoms out in anything but a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return '.'.join(reversed(parts))
    return None


@dataclasses.dataclass
class FunctionInfo:
    qualname: str
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    params: List[str]
    defaults: Dict[str, ast.expr]


class ModuleIndex:
    """One file, parsed once: functions by qualname + call sites by
    simple callee name (``self.f(...)`` and bare ``f(...)`` both index
    under ``f``)."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.tree = ast.parse(text)
        self.lines = text.splitlines()
        self.functions: Dict[str, FunctionInfo] = {}
        # simple callee name -> [(caller FunctionInfo, Call node)]
        self.call_sites: Dict[str, List[Tuple[FunctionInfo,
                                              ast.Call]]] = {}
        self._index(self.tree.body, prefix='')
        for info in self.functions.values():
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                name = self._callee_simple_name(node.func)
                if name is not None:
                    self.call_sites.setdefault(name, []).append(
                        (info, node))

    @staticmethod
    def _callee_simple_name(func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            return func.id
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id == 'self':
            return func.attr
        return None

    def _index(self, body: Sequence[ast.stmt], prefix: str) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f'{prefix}{node.name}'
                args = node.args
                params = ([a.arg for a in args.posonlyargs] +
                          [a.arg for a in args.args] +
                          [a.arg for a in args.kwonlyargs])
                defaults: Dict[str, ast.expr] = {}
                pos = ([a.arg for a in args.posonlyargs] +
                       [a.arg for a in args.args])
                for name, dflt in zip(pos[len(pos) - len(args.defaults):],
                                      args.defaults):
                    defaults[name] = dflt
                for a, dflt in zip(args.kwonlyargs, args.kw_defaults):
                    if dflt is not None:
                        defaults[a.arg] = dflt
                self.functions[qual] = FunctionInfo(qual, node, params,
                                                    defaults)
                self._index(node.body, prefix=f'{qual}.')
            elif isinstance(node, ast.ClassDef):
                self._index(node.body, prefix=f'{prefix}{node.name}.')
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For,
                                   ast.While)):
                self._index(_suites(node), prefix=prefix)

    def find(self, name: str) -> Optional[FunctionInfo]:
        """Exact qualname, else unique ``...name`` suffix match."""
        if name in self.functions:
            return self.functions[name]
        hits = [f for q, f in self.functions.items()
                if q.endswith('.' + name)]
        return hits[0] if len(hits) == 1 else None


def _suites(node: ast.stmt) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    for field in ('body', 'orelse', 'finalbody'):
        out.extend(getattr(node, field, ()) or ())
    for handler in getattr(node, 'handlers', ()) or ():
        out.extend(handler.body)
    return out


def local_defs(fn_node: ast.AST) -> Dict[str, List[ast.expr]]:
    """name -> every expression assigned to it inside the function
    (nested defs excluded).  Tuple/list unpack targets map each name to
    the whole RHS wrapped as an unknown marker unless it is the
    single-element ``[x] = rhs`` form, which maps to the RHS call."""
    defs: Dict[str, List[ast.expr]] = {}

    def add(name: str, expr: ast.expr) -> None:
        defs.setdefault(name, []).append(expr)

    for node in _walk_no_nested(fn_node):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                _bind_target(tgt, node.value, add)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            _bind_target(node.target, node.value, add)
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            add(node.target.id, node)        # opaque: x op= ...
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            _bind_target(node.target, node, add)   # loop var: opaque
        elif isinstance(node, ast.withitem) and \
                node.optional_vars is not None:
            _bind_target(node.optional_vars, node.context_expr, add)
        elif isinstance(node, ast.NamedExpr) and \
                isinstance(node.target, ast.Name):
            add(node.target.id, node.value)
    return defs


def _bind_target(tgt, value, add) -> None:
    if isinstance(tgt, ast.Name):
        add(tgt.id, value)
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        if len(tgt.elts) == 1 and isinstance(tgt.elts[0], ast.Name):
            add(tgt.elts[0].id, value)       # [x] = call()
        else:
            for el in tgt.elts:
                if isinstance(el, ast.Name):
                    add(el.id, _OPAQUE)


class _Opaque(ast.expr):
    """Sentinel def expression for bindings the walk cannot model."""


_OPAQUE = _Opaque()

# Nodes whose operands simply pass through source resolution.
_TRANSPARENT_UNARY = (ast.UnaryOp, ast.Starred, ast.Await,
                      ast.FormattedValue)


def _walk_no_nested(fn_node: ast.AST):
    """ast.walk over a function body that does not descend into nested
    function/class definitions."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def resolve_sources(index: ModuleIndex, fn: FunctionInfo,
                    expr: ast.expr, depth: int = 4,
                    _seen: Optional[Set[Tuple[str, str]]] = None
                    ) -> Set[Source]:
    """The source atoms feeding ``expr`` inside ``fn`` (see module
    docstring).  Arithmetic/boolean/conditional operators union their
    operands; parameters resolve through caller argument expressions
    while ``depth`` lasts."""
    seen = _seen if _seen is not None else set()
    if isinstance(expr, _Opaque):
        return {Source('unknown', 'unpacked binding')}
    if isinstance(expr, ast.Constant):
        return {Source('const', repr(expr.value))}
    if isinstance(expr, ast.Name):
        return _resolve_name(index, fn, expr.id, depth, seen)
    if isinstance(expr, ast.Attribute):
        dotted = dotted_name(expr)
        if dotted is not None:
            return {Source('attr', dotted)}
        return {Source('unknown', 'attribute of non-name')}
    if isinstance(expr, ast.Call):
        dotted = dotted_name(expr.func)
        return {Source('call', dotted if dotted is not None
                       else type(expr.func).__name__)}
    if isinstance(expr, ast.BinOp):
        return (resolve_sources(index, fn, expr.left, depth, seen) |
                resolve_sources(index, fn, expr.right, depth, seen))
    if isinstance(expr, _TRANSPARENT_UNARY):
        inner = getattr(expr, 'operand', None) or \
            getattr(expr, 'value', None)
        if inner is not None:
            return resolve_sources(index, fn, inner, depth, seen)
    if isinstance(expr, ast.BoolOp):
        out: Set[Source] = set()
        for v in expr.values:
            out |= resolve_sources(index, fn, v, depth, seen)
        return out
    if isinstance(expr, ast.IfExp):
        return (resolve_sources(index, fn, expr.body, depth, seen) |
                resolve_sources(index, fn, expr.orelse, depth, seen))
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = set()
        for el in expr.elts:
            out |= resolve_sources(index, fn, el, depth, seen)
        return out
    if isinstance(expr, ast.Compare):
        return {Source('const', 'bool')}
    if isinstance(expr, ast.Subscript):
        base = dotted_name(expr.value)
        if base is not None:
            return {Source('attr', base + '[]')}
        return {Source('unknown', 'subscript')}
    return {Source('unknown', type(expr).__name__)}


def _resolve_name(index: ModuleIndex, fn: FunctionInfo, name: str,
                  depth: int, seen: Set[Tuple[str, str]]) -> Set[Source]:
    key = (fn.qualname, name)
    if key in seen:
        return {Source('unknown', f'cycle through {name!r}')}
    seen = seen | {key}
    defs = _defs_cache(index, fn)
    if name in defs:
        out: Set[Source] = set()
        for d in defs[name]:
            out |= resolve_sources(index, fn, d, depth, seen)
        return out
    if name in fn.params:
        if depth <= 0:
            return {Source('param', f'{fn.qualname}.{name}')}
        out = set()
        callers = index.call_sites.get(
            fn.qualname.rsplit('.', 1)[-1], [])
        for caller, call in callers:
            if caller.qualname == fn.qualname:
                continue
            arg = _arg_for_param(fn, call, name)
            if arg is None:
                if name in fn.defaults:
                    arg = fn.defaults[name]
                else:
                    out.add(Source('param', f'{fn.qualname}.{name}'))
                    continue
            out |= resolve_sources(index, caller, arg, depth - 1, seen)
        if not out:
            if name in fn.defaults:
                return resolve_sources(index, fn, fn.defaults[name],
                                       depth - 1, seen)
            return {Source('param', f'{fn.qualname}.{name}')}
        return out
    return {Source('unknown', f'unbound name {name!r}')}


def _arg_for_param(fn: FunctionInfo, call: ast.Call,
                   param: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    params = fn.params
    if params and params[0] == 'self':
        params = params[1:]
    try:
        pos = params.index(param)
    except ValueError:
        return None
    if pos < len(call.args) and not any(
            isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
        return call.args[pos]
    return None


_DEFS_ATTR = '_skycheck_defs'


def _defs_cache(index: ModuleIndex, fn: FunctionInfo):
    cached = getattr(fn, _DEFS_ATTR, None)
    if cached is None:
        cached = local_defs(fn.node)
        setattr(fn, _DEFS_ATTR, cached)
    return cached


# ------------------------------------------------------- dict-key lattice

_TYPE_MAP = {int: 'number', float: 'number', str: 'str', bool: 'bool',
             type(None): 'none'}
_CAST_TYPES = {'int': 'number', 'float': 'number', 'len': 'number',
               'sum': 'number', 'round': 'number', 'str': 'str',
               'bool': 'bool', 'dict': 'dict', 'list': 'list',
               'sorted': 'list', 'tuple': 'list', 'set': 'list',
               'min': 'number', 'max': 'number', 'abs': 'number'}


def infer_value_type(index: ModuleIndex, fn: FunctionInfo,
                     expr: ast.expr) -> str:
    """Best-effort concrete JSON type of ``expr``; 'unknown' never
    conflicts with anything."""
    if isinstance(expr, ast.Constant):
        return _TYPE_MAP.get(type(expr.value), 'unknown')
    if isinstance(expr, ast.Dict) or isinstance(expr, ast.DictComp):
        return 'dict'
    if isinstance(expr, (ast.List, ast.ListComp, ast.Tuple)):
        return 'list'
    if isinstance(expr, ast.Compare):
        return 'bool'
    if isinstance(expr, ast.IfExp):
        a = infer_value_type(index, fn, expr.body)
        b = infer_value_type(index, fn, expr.orelse)
        if a == b:
            return a
        if 'none' in (a, b):        # Optional[x]: x or null
            return a if b == 'none' else b
        return 'unknown'
    if isinstance(expr, ast.Call):
        f = expr.func
        if isinstance(f, ast.Name) and f.id in _CAST_TYPES:
            return _CAST_TYPES[f.id]
        if isinstance(f, ast.Attribute) and f.attr in ('get',):
            return 'unknown'
        return 'unknown'
    if isinstance(expr, ast.BinOp):
        a = infer_value_type(index, fn, expr.left)
        b = infer_value_type(index, fn, expr.right)
        if 'number' in (a, b):
            return 'number'
        return 'unknown'
    if isinstance(expr, ast.Name):
        info = _defs_cache(index, fn).get(expr.id)
        if info and len(info) == 1 and not isinstance(info[0], _Opaque):
            if isinstance(info[0], ast.stmt):
                return 'unknown'
            return infer_value_type(index, fn, info[0])
        return 'unknown'
    return 'unknown'


@dataclasses.dataclass
class KeyModel:
    """Produced keys of one payload: key -> (always, types, lines)."""
    always: Set[str] = dataclasses.field(default_factory=set)
    sometimes: Set[str] = dataclasses.field(default_factory=set)
    types: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    lines: Dict[str, int] = dataclasses.field(default_factory=dict)
    complete: bool = True          # False when **spread went unresolved

    @property
    def keys(self) -> Set[str]:
        return self.always | self.sometimes

    def merge_branch(self, other: 'KeyModel') -> None:
        """Combine two alternative branches: always = intersection."""
        self.sometimes |= ((self.always ^ other.always) |
                           other.sometimes)
        self.always &= other.always
        self.sometimes -= self.always
        for k, t in other.types.items():
            self.types.setdefault(k, set()).update(t)
        for k, ln in other.lines.items():
            self.lines.setdefault(k, ln)
        self.complete = self.complete and other.complete


def _literal_keys(index: ModuleIndex, fn: FunctionInfo,
                  node: ast.Dict, model: KeyModel,
                  conditional: bool, depth: int = 3) -> None:
    for k, v in zip(node.keys, node.values):
        if k is None:                       # {**spread}
            resolved = False
            if isinstance(v, ast.Name):
                defs = _defs_cache(index, fn).get(v.id, [])
                for d in defs:
                    if isinstance(d, ast.Dict) and depth > 0:
                        _literal_keys(index, fn, d, model,
                                      conditional, depth - 1)
                        resolved = True
            if not resolved:
                model.complete = False
            continue
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            _note_key(index, fn, model, k.value, v, conditional,
                      k.lineno)
        else:
            model.complete = False


def _note_key(index: ModuleIndex, fn: FunctionInfo, model: KeyModel,
              key: str, value: ast.expr, conditional: bool,
              lineno: int) -> None:
    if conditional:
        if key not in model.always:
            model.sometimes.add(key)
    else:
        model.always.add(key)
        model.sometimes.discard(key)
    t = infer_value_type(index, fn, value)
    if t != 'unknown':
        model.types.setdefault(key, set()).add(t)
    model.lines.setdefault(key, lineno)


def _is_conditional(fn_node: ast.AST, target: ast.AST) -> bool:
    """True when ``target`` sits under an If/Try/loop inside the
    function (i.e. does not execute on every call)."""
    for holder in ast.walk(fn_node):
        if holder is fn_node or not isinstance(
                holder, (ast.If, ast.Try, ast.For, ast.While,
                         ast.ExceptHandler)):
            continue
        if any(child is target for child in ast.walk(holder)):
            return True
    return False


def _apply_var_mutations(index: ModuleIndex, fn: FunctionInfo,
                         var: str, model: KeyModel) -> None:
    """Fold ``var['k'] = v`` / ``var.update({...})`` /
    ``var.setdefault('k', v)`` statements into the model."""
    for node in _walk_no_nested(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Subscript) and \
                isinstance(node.targets[0].value, ast.Name) and \
                node.targets[0].value.id == var:
            sl = node.targets[0].slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value,
                                                           str):
                cond = _is_conditional(fn.node, node)
                _note_key(index, fn, model, sl.value, node.value,
                          cond, node.lineno)
            else:
                model.complete = False
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == var:
            cond = _is_conditional(fn.node, node)
            if node.func.attr == 'update' and node.args and \
                    isinstance(node.args[0], ast.Dict):
                _literal_keys(index, fn, node.args[0], model, cond)
            elif node.func.attr == 'setdefault' and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                val = (node.args[1] if len(node.args) > 1
                       else ast.Constant(value=None))
                _note_key(index, fn, model, node.args[0].value, val,
                          cond, node.lineno)


def _resolve_payload_expr(index: ModuleIndex, fn: FunctionInfo,
                          expr: ast.expr, conditional: bool
                          ) -> KeyModel:
    model = KeyModel()
    if isinstance(expr, ast.Dict):
        _literal_keys(index, fn, expr, model, conditional)
    elif isinstance(expr, ast.Name):
        defs = _defs_cache(index, fn).get(expr.id, [])
        dict_defs = [d for d in defs if isinstance(d, ast.Dict)]
        if dict_defs:
            branch = None
            for d in dict_defs:
                m = KeyModel()
                _literal_keys(index, fn, d, m, conditional)
                if branch is None:
                    branch = m
                else:
                    branch.merge_branch(m)
            model = branch or model
        else:
            model.complete = False
        _apply_var_mutations(index, fn, expr.id, model)
    elif isinstance(expr, ast.Call):
        dotted = dotted_name(expr.func)
        callee = index.find(dotted.rsplit('.', 1)[-1]) if dotted else \
            None
        if callee is not None:
            return dict_key_model(index, callee, ('return',))
        model.complete = False
    else:
        model.complete = False
    return model


def dict_key_model(index: ModuleIndex, fn: FunctionInfo,
                   mode: Tuple[str, ...]) -> KeyModel:
    """The produced-key lattice of a function's payload.

    mode:
      ('return',)       union of all ``return {...}`` branches
      ('var', NAME)     dict bound to NAME + its ``NAME[k]=`` mutations
      ('call', FUNC)    first argument of every ``FUNC(...)`` call
    """
    kind = mode[0]
    if kind == 'return':
        branch: Optional[KeyModel] = None
        for node in _walk_no_nested(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                m = _resolve_payload_expr(index, fn, node.value, False)
                if branch is None:
                    branch = m
                else:
                    branch.merge_branch(m)
        return branch if branch is not None else KeyModel(complete=False)
    if kind == 'var':
        name_expr = ast.Name(id=mode[1])
        return _resolve_payload_expr(index, fn, name_expr, False)
    if kind == 'call':
        branch = None
        for node in _walk_no_nested(fn.node):
            if isinstance(node, ast.Call) and node.args:
                callee = node.func
                simple = (callee.id if isinstance(callee, ast.Name)
                          else callee.attr
                          if isinstance(callee, ast.Attribute) else None)
                if simple == mode[1]:
                    m = _resolve_payload_expr(index, fn, node.args[0],
                                              False)
                    if branch is None:
                        branch = m
                    else:
                        branch.merge_branch(m)
        return branch if branch is not None else KeyModel(complete=False)
    raise ValueError(f'unknown dict_key_model mode {mode!r}')


# ------------------------------------------------------- consumed keys

def read_keys(index: ModuleIndex, fn: FunctionInfo,
              varnames: Optional[Sequence[str]] = None,
              exclude_vars: Sequence[str] = (),
              scope: Optional[ast.AST] = None) -> Dict[str, int]:
    """String keys the function READS: ``X['k']`` loads and
    ``X.get('k'...)`` calls, restricted to receivers named in
    ``varnames`` (None = any receiver, except names in
    ``exclude_vars`` — receivers holding some *other* surface's
    document).  ``scope`` restricts the walk to one statement subtree
    of the function (e.g. a single route branch of a multi-route
    handler).  Returns key -> first line."""
    out: Dict[str, int] = {}

    def receiver_ok(node: ast.expr) -> bool:
        if varnames is None:
            if isinstance(node, ast.Name) and node.id in exclude_vars:
                return False
            return True
        if isinstance(node, ast.Name):
            return node.id in varnames
        return False

    for node in _walk_no_nested(scope if scope is not None
                                else fn.node):
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.slice, ast.Constant) and \
                isinstance(node.slice.value, str) and \
                receiver_ok(node.value):
            out.setdefault(node.slice.value, node.lineno)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ('get', 'pop') and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str) and \
                receiver_ok(node.func.value):
            out.setdefault(node.args[0].value, node.lineno)
        elif isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                receiver_ok(node.comparators[0]):
            out.setdefault(node.left.value, node.lineno)
    return out
